"""GUPPI RAW → high-resolution filterbank reduction: the TPU compute core.

This is the per-``BLP<band><bank>`` worker reduction the reference delegates
to ``rawspec`` on CUDA nodes (SURVEY.md §0: products ``*_<scan>.rawspec.NNNN``;
BASELINE.json config 2).  The rebuild is pure JAX — everything here is
jittable with static shapes, so XLA fuses dequantization, the polyphase
frontend, Stokes detection and integration around the FFT:

    int8 voltages (nchan_coarse, ntime, npol, 2)
      → dequant (float32 complex)
      → 4-tap polyphase filter bank frontend (windowed-sinc FIR)
      → nfft-point FFT per coarse channel  (four-step for the 1M-pt case)
      → fftshift (DC lands at fine index nfft//2 — exactly where the
        reference's despike expects it, src/gbt.jl:101-111)
      → Stokes detect (I / XXYY / full-pol / IQUV)
      → time integrate by ``nint``
      → (ntime_out, nif, nchan_coarse*nfft) float32 filterbank slab

TPU notes (pallas_guide.md; SURVEY.md §7 "hard parts"):

- The 1M-point FFT exceeds VMEM as a monolith.  ``fft`` therefore factors
  N = N1·N2 and runs two batched small FFTs plus a twiddle multiply (the
  classic four-step decomposition) — each stage is a contiguous batch of
  ≤8K-point FFTs that XLA tiles comfortably; the twiddle and transpose fuse.
- All control flow is static; ``jax.lax`` only.  No data-dependent shapes.
- The FIR stage runs on separate real/imag float32 planes (``dequantize``),
  keeping it real-valued VPU/MXU work; the FFT recombines via
  ``lax.complex``.
"""

from __future__ import annotations

import functools
import math
from typing import Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from blit.ops import dft as dftmod
from blit.ops.fqav import fqav as _fqav

STOKES_NIF = {"I": 1, "XX": 1, "YY": 1, "XXYY": 2, "full": 4, "IQUV": 4}

# Largest FFT run as a single jnp.fft call; above this, four-step decompose.
_DIRECT_FFT_MAX = 8192

# Backends with no complex-dtype / FFT-HLO support: planar matmul DFT only.
_MATMUL_ONLY_BACKENDS = ("tpu", "axon")


def usable_frames(nsamps: int, nfft: int, ntap: int, nint: int) -> int:
    """Whole PFB frames a gap-free span of ``nsamps`` samples yields, rounded
    down to the integration length — THE frame-accounting invariant shared by
    the streaming flush (blit/pipeline.py) and the mesh scan loader
    (blit/parallel/scan.py)."""
    frames = nsamps // nfft - ntap + 1
    return (frames // nint) * nint if frames > 0 else 0


def pfb_coeffs(ntap: int, nfft: int, window: str = "hamming") -> np.ndarray:
    """Windowed-sinc prototype filter for the polyphase frontend, shaped
    ``(ntap, nfft)`` and normalized to unit DC gain per fine channel.

    Matches the standard rawspec/CASPER design: ``sinc(x)·w(n)`` over
    ``ntap*nfft`` taps with the sinc main lobe spanning one fine channel.
    """
    n = np.arange(ntap * nfft, dtype=np.float64)
    x = n / nfft - ntap / 2.0
    sinc = np.sinc(x)
    if window == "hamming":
        win = np.hamming(ntap * nfft)
    elif window == "hanning":
        win = np.hanning(ntap * nfft)
    elif window == "rect":
        win = np.ones(ntap * nfft)
    else:
        raise ValueError(f"unknown window {window!r}")
    h = sinc * win
    h /= h.sum()  # unit DC gain: a constant input yields 1.0 in the DC bin pre-FFT-scaling
    return h.reshape(ntap, nfft).astype(np.float32)


def dequantize(voltages: jax.Array, dtype=jnp.float32) -> Tuple[jax.Array, jax.Array]:
    """int8 GUPPI voltages ``(..., 2)`` (re, im) → real/imag float pair.

    Returns separate real and imaginary parts rather than a complex dtype so
    the FIR stage runs real-valued on the VPU/MXU; the FFT stage recombines.
    """
    v = voltages.astype(dtype)
    return v[..., 0], v[..., 1]


def pfb_frontend(
    x: jax.Array,
    coeffs: jax.Array,
) -> jax.Array:
    """Polyphase FIR: frame ``x`` (..., ntime) into windows of ``nfft`` and
    produce tap-weighted frame sums ``(..., nframes, nfft)`` where
    ``nframes = ntime//nfft - ntap + 1``.

    ``ntime`` must be a multiple of ``nfft``.  Works on real or complex
    inputs (applied separately to re/im keeps everything real).
    """
    ntap, nfft = coeffs.shape
    ntime = x.shape[-1]
    if ntime % nfft:
        raise ValueError(f"pfb_frontend: ntime={ntime} not a multiple of nfft={nfft}")
    nblk = ntime // nfft
    nframes = nblk - ntap + 1
    if nframes < 1:
        raise ValueError(f"pfb_frontend: need >= {ntap} blocks of {nfft}, got {nblk}")
    blocks = x.reshape(x.shape[:-1] + (nblk, nfft))
    # ntap is tiny (4): unrolled shifted-slice sum; XLA fuses this into one
    # vectorized pass, no gather needed.
    acc = coeffs[0] * blocks[..., 0:nframes, :]
    for k in range(1, ntap):
        acc = acc + coeffs[k] * blocks[..., k : k + nframes, :]
    return acc


def _four_step_factors(n: int) -> Tuple[int, int]:
    """Split n = n1*n2 with n1, n2 as close as possible (prefer powers of 2)."""
    if n & (n - 1) == 0:  # power of two
        p = n.bit_length() - 1
        n1 = 1 << (p // 2)
        return n1, n // n1
    n1 = int(math.isqrt(n))
    while n % n1:
        n1 -= 1
    return n1, n // n1


def resolve_fft_method(method: str, n: int) -> str:
    """Resolve ``"auto"`` to a concrete FFT strategy for the current backend.

    On backends without complex-dtype support (this TPU generation — probed:
    no FFT HLO, no complex matmul) the only path is the planar matmul DFT
    (:mod:`blit.ops.dft`), which is also the MXU-preferred design.  On
    CPU/GPU, native complex FFTs win: direct for small N, four-step above.
    """
    if method != "auto":
        return method
    if jax.default_backend() in _MATMUL_ONLY_BACKENDS:
        return "matmul"
    return "direct" if n <= _DIRECT_FFT_MAX else "four_step"


def fft_planar(
    fr: jax.Array,
    fi: jax.Array,
    *,
    method: str = "auto",
    precision=None,
    dtype: str = "float32",
    order: str = "natural",
) -> Tuple[jax.Array, jax.Array]:
    """Planar (re, im) FFT along the last axis — the dispatch point between
    the complex-dtype XLA paths and the TPU matmul-DFT path.

    ``dtype``: working dtype of the matmul-DFT stages ("float32" |
    "bfloat16").  bf16 halves the HBM held by the inter-stage intermediates
    — the lever that lets more frames fit per dispatch (DESIGN.md §3) — at
    a measured spectral accuracy cost comparable to the MXU's default
    bf16-grade multiplies (DESIGN.md §1).  Complex-FFT backends ignore it.

    ``order="twisted"`` (matmul path only) skips the DFT's per-level
    untwist transposes and emits the digit-permuted spectrum that
    :func:`blit.ops.dft.untwist` restores — for order-oblivious consumers
    (power detection) that can untwist their smaller output instead.
    Complex-FFT methods always emit natural order.
    """
    method = resolve_fft_method(method, fr.shape[-1])
    if method == "matmul":
        if dtype != "float32":
            fr = fr.astype(dtype)
            fi = fi.astype(dtype)
        return dftmod.dft(fr, fi, precision=precision, dtype=dtype,
                          order=order)
    # Complex-FFT backends (CPU/GPU) reject bf16 planes: upcast those —
    # the bf16-staged collective paths stay correct off-TPU, they just
    # lose the traffic saving the TPU matmul path keeps.  (Only bf16:
    # f64 planes must keep flowing into a complex128 FFT.)
    if fr.dtype == jnp.bfloat16:
        fr = fr.astype(jnp.float32)
        fi = fi.astype(jnp.float32)
    z = fft(jax.lax.complex(fr, fi), method=method)
    return jnp.real(z), jnp.imag(z)


def fft(z: jax.Array, *, method: str = "auto") -> jax.Array:
    """Complex FFT along the last axis (CPU/GPU paths).

    ``method``:
      - ``"direct"``: one ``jnp.fft.fft`` call.
      - ``"four_step"``: N = N1·N2 decomposition — two batched small FFTs +
        twiddle multiply + transpose.  This keeps every sub-FFT's working set
        VMEM-sized and its batch MXU/VPU-friendly; required for the 1M-point
        hi-res product (SURVEY.md §7 "hard parts").
      - ``"auto"``: direct for N <= 8192, four-step above.
    """
    n = z.shape[-1]
    if method == "auto":
        method = "direct" if n <= _DIRECT_FFT_MAX else "four_step"
    if method == "direct":
        return jnp.fft.fft(z)
    if method != "four_step":
        raise ValueError(f"unknown fft method {method!r}")
    n1, n2 = _four_step_factors(n)
    if n1 == 1:
        return jnp.fft.fft(z)
    # x[n] with n = N2*j1 + j2  →  view (n1, n2): rows index j1.
    x = z.reshape(z.shape[:-1] + (n1, n2))
    # Stage 1: length-N1 FFTs down the columns (axis -2).
    a = jnp.fft.fft(x, axis=-2)
    # Twiddle W_N^{j2*k1}: shape (n1, n2) (k1 rows, j2 cols).
    k1 = np.arange(n1).reshape(n1, 1)
    j2 = np.arange(n2).reshape(1, n2)
    tw = np.exp(-2j * np.pi * (k1 * j2) / n).astype(np.complex64)
    a = a * jnp.asarray(tw)
    # Stage 2: length-N2 FFTs along the rows; X[k1 + N1*k2] = b[k1, k2].
    b = jnp.fft.fft(a, axis=-1)
    return jnp.swapaxes(b, -1, -2).reshape(z.shape)


def detect_stokes_planar(
    sr: jax.Array, si: jax.Array, stokes: str
) -> jax.Array:
    """Detect planar spectra (re, im), each (..., npol, nframes, nfft) →
    power products (..., nif, nframes, nfft) float32.

    Products (rawspec conventions, SURVEY.md §0):
      - ``"I"``:    |X|² + |Y|²                       (nif=1)
      - ``"XX"``/``"YY"``: single-pol power           (nif=1)
      - ``"XXYY"``: [|X|², |Y|²]                      (nif=2)
      - ``"full"``: [|X|², |Y|², Re(XY*), Im(XY*)]    (nif=4)
      - ``"IQUV"``: Stokes parameters                 (nif=4)
    Single-pol input only supports total power.
    """
    npol = sr.shape[-3]
    if npol == 1:
        if stokes not in ("I", "XX"):
            raise ValueError(f"stokes={stokes!r} needs 2 pols, got 1")
        p = (sr**2 + si**2)[..., 0, :, :]
        return p[..., None, :, :]
    xr, yr = sr[..., 0, :, :], sr[..., 1, :, :]
    xi, yi = si[..., 0, :, :], si[..., 1, :, :]
    xx = xr**2 + xi**2
    yy = yr**2 + yi**2
    if stokes == "I":
        return (xx + yy)[..., None, :, :]
    if stokes == "XX":
        return xx[..., None, :, :]
    if stokes == "YY":
        return yy[..., None, :, :]
    if stokes == "XXYY":
        return jnp.stack([xx, yy], axis=-3)
    # X·conj(Y):
    xy_re = xr * yr + xi * yi
    xy_im = xi * yr - xr * yi
    if stokes == "full":
        return jnp.stack([xx, yy, xy_re, xy_im], axis=-3)
    if stokes == "IQUV":
        return jnp.stack([xx + yy, xx - yy, 2 * xy_re, -2 * xy_im], axis=-3)
    raise ValueError(f"unknown stokes {stokes!r}")


def detect_stokes(spec: jax.Array, stokes: str) -> jax.Array:
    """Complex-dtype convenience wrapper over :func:`detect_stokes_planar`
    (CPU/GPU callers; the TPU path stays planar throughout)."""
    return detect_stokes_planar(jnp.real(spec), jnp.imag(spec), stokes)


def integrate(power: jax.Array, nint: int) -> jax.Array:
    """Sum groups of ``nint`` consecutive frames (axis -2)."""
    if nint <= 1:
        return power
    nframes = power.shape[-2]
    if nframes % nint:
        raise ValueError(f"integrate: nint={nint} does not divide nframes={nframes}")
    shape = power.shape[:-2] + (nframes // nint, nint, power.shape[-1])
    return power.reshape(shape).sum(axis=-2)


# Kernel resolution of the most recent channelize trace (see the
# assignment inside channelize; read via last_kernel_plan()).
_LAST_PLAN: dict = {}


def last_kernel_plan() -> dict:
    """The kernel plan the most recent :func:`channelize` TRACE resolved
    ('auto' dispatch made concrete: which pallas fusions ran).  Empty until
    a trace happens; a jit cache hit does not refresh it."""
    return dict(_LAST_PLAN)


@functools.partial(
    jax.jit,
    static_argnames=(
        "nfft", "ntap", "nint", "stokes", "fft_method", "precision",
        "channel_block", "dtype", "fqav_by", "dft_order", "pfb_kernel",
        "detect_kernel", "tail_kernel",
    ),
)
def channelize(
    voltages: jax.Array,
    coeffs: jax.Array,
    *,
    nfft: int,
    ntap: int = 4,
    nint: int = 1,
    stokes: str = "I",
    fft_method: str = "auto",
    precision: Optional[str] = None,
    channel_block: int = 0,
    dtype: str = "float32",
    fqav_by: int = 1,
    dft_order: str = "auto",
    pfb_kernel: str = "auto",
    detect_kernel: str = "auto",
    tail_kernel: str = "auto",
) -> jax.Array:
    """The full single-chip reduction: int8 voltage block → filterbank slab.

    Args:
      voltages: int8 ``(nchan_coarse, ntime, npol, 2)`` (GuppiRaw.read_block
        layout, blit/io/guppi.py) with ``ntime`` a multiple of ``nfft`` and
        ``ntime//nfft >= ntap + nint - 1``.
      coeffs: ``(ntap, nfft)`` PFB prototype from :func:`pfb_coeffs`.
      nfft: fine channels per coarse channel (the rawspec product size; 2**20
        for the hi-res product).
      nint: spectra integrated per output sample.
      stokes: detection product (see :func:`detect_stokes_planar`).
      fft_method: "auto" | "direct" | "four_step" | "matmul" (see
        :func:`resolve_fft_method`; "auto" picks "matmul" on TPU).
      precision: matmul precision for the "matmul" path — None (backend
        default; bf16-grade multiplies on the MXU) or "highest" (full f32,
        ~3x the MXU passes).
      channel_block: if > 0 and < nchan, process coarse channels in groups
        of this size via ``lax.map`` *inside* one device program — large
        per-dispatch work (amortizing dispatch latency) at bounded peak HBM
        (the hi-res 1M-point intermediates are what overflow otherwise).
      dtype: working dtype from dequantization through the FFT stages
        ("float32" | "bfloat16").  bfloat16 halves the HBM every
        intermediate occupies — the f32 dequant/PFB planes were the peak
        residents — fitting ~2x the frames per dispatch; int8 voltages
        carry exactly bf16's 8 mantissa bits, and the detected powers
        still accumulate in float32 (the MXU truncates matmul products to
        bf16 grade by default anyway).  Measured accuracy: DESIGN.md §8.
      fqav_by: on-device frequency-averaging epilogue — sum every
        ``fqav_by`` consecutive fine channels (reference ``fqav`` default-f
        semantics, src/gbtworkerfunctions.jl:16-20) before anything leaves
        the chip, shrinking the product (and any host readback) by that
        factor.  Callers must map the channel axis with
        :func:`blit.ops.fqav.fqav_range`.

    Returns:
      float32 ``(ntime_out, nif, nchan_coarse*nfft)`` in blit's canonical
      ``(time, pol, chan)`` layout — channel fastest, fine channels fftshifted
      within each coarse channel so the DC artifact sits at fine index
      ``nfft//2`` (despike parity, blit/ops/despike.py).
    """
    nchan, _, npol, _ = voltages.shape
    if precision == "highest":
        prec = jax.lax.Precision.HIGHEST
    elif precision is None:
        prec = None
    else:
        raise ValueError(f"precision must be None or 'highest', got {precision!r}")
    if nfft % 2:
        raise ValueError("channelize: nfft must be even")
    # Fold the fftshift into the window via the shift theorem: multiplying
    # the DFT input by (-1)^j rolls the spectrum by nfft/2, so the shifted
    # coefficients make the FFT emit fftshifted order directly — two fewer
    # full-array HBM passes.  (Frame sample index ≡ j mod 2 because nfft is
    # even, so the sign pattern is tap-independent.)
    sign = jnp.asarray(
        np.where(np.arange(nfft) % 2 == 0, 1.0, -1.0).astype(np.float32)
    )
    shifted_coeffs = coeffs * sign[None, :]

    if dtype not in ("float32", "bfloat16"):
        raise ValueError(f"dtype must be float32 or bfloat16, got {dtype!r}")
    if fqav_by > 1 and nfft % fqav_by:
        # nchan*nfft divisibility alone would let averaging groups straddle
        # coarse-channel boundaries, corrupting nfpc-keyed consumers.
        raise ValueError(f"fqav_by={fqav_by} does not divide nfft={nfft}")

    # bf16 mode applies from dequantization on: the int8 voltages carry 8
    # significant bits, exactly bf16's mantissa, so the dequant planes and
    # the 4-tap PFB lose nothing material in half-width — and the f32
    # dequant/PFB intermediates were the peak-HBM residents that capped
    # frames-per-dispatch (the gross (ntap-1+frames)/frames factor makes
    # them BIGGER than the DFT intermediates).  Accuracy is pinned by
    # tests/test_channelize.py::test_bfloat16_stage_dtype_close_to_golden.
    work_dtype = jnp.bfloat16 if dtype == "bfloat16" else jnp.float32
    wcoeffs = shifted_coeffs.astype(work_dtype)

    # dft_order: "twisted" runs the matmul DFT in digit-permuted order
    # (skipping its per-level transposes; detection is elementwise so the
    # permutation rides through free) and untwists ONCE on the detected
    # power.  Analytically that saves one full pass of traffic — but the
    # interleaved A/B on the chip measured it ~20% SLOWER (4.08 vs
    # 5.06 GB/s at the bf16 bench config): the reversed multi-axis power
    # transpose lowers worse than the two spectra swapaxes XLA fuses.
    # "auto" therefore = "natural"; the twisted path stays as a verified-
    # correct tuning knob (see DESIGN.md §9).
    if dft_order not in ("auto", "twisted", "natural"):
        raise ValueError(f"bad dft_order {dft_order!r}")
    resolved = resolve_fft_method(fft_method, nfft)
    twisted = resolved == "matmul" and dft_order == "twisted"

    # pfb_kernel: "pallas" fuses dequant + FIR into one VMEM-resident pass
    # (blit/ops/pallas_pfb.py — the fix for the roofline's dominant stage,
    # DESIGN.md §9): the int8 voltages are read once and the gross
    # dequantized planes never exist in HBM.  Interleaved A/B on the chip:
    # pallas 5.9-6.3 vs xla 4.86 GB/s end-to-end at the bf16 bench config,
    # so "auto" = pallas on the matmul backends (the real chip) and the
    # jnp path elsewhere (interpret-mode pallas is for tests only).  The
    # kernel needs npol=2 int8 input; other shapes fall back.
    if pfb_kernel not in ("auto", "xla", "pallas", "fused1"):
        raise ValueError(f"bad pfb_kernel {pfb_kernel!r}")
    backend = jax.default_backend()
    pol_ok = voltages.shape[2] == 2 and voltages.shape[3] == 2
    if pfb_kernel == "auto":
        from blit.ops import pallas_pfb

        # Prefer the fullest fusion that compiles natively AND fits the
        # VMEM budget: fused1 (dequant+PFB+DFT stage 1; interleaved A/B
        # 8.3-8.7 vs 6.4 GB/s) → pallas (dequant+PFB) → xla.  Large-
        # nframes chunks (e.g. the '0002' preset) exceed any fine tile
        # and take the XLA path.
        nblk = voltages.shape[1] // nfft
        pfb_kernel = "xla"
        if backend in _MATMUL_ONLY_BACKENDS and pol_ok:
            # default_factors only inside the matmul guard: the FFT paths
            # accept nfft values it cannot factor.
            factors = (
                dftmod.default_factors(nfft) if resolved == "matmul" else ()
            )
            if (
                len(factors) >= 2
                and not twisted  # fused1 ignores dft_order='twisted'
                and pallas_pfb.fused1_fits(
                    nfft, nblk, ntap, factors[0], dtype
                )
            ):
                pfb_kernel = "fused1"
            elif pallas_pfb.fits(nfft, nblk, ntap, dtype):
                pfb_kernel = "pallas"
    elif pfb_kernel in ("pallas", "fused1"):
        if not pol_ok:
            raise ValueError(
                f"pfb_kernel={pfb_kernel!r} needs npol=2 complex int8"
            )
        if backend not in _MATMUL_ONLY_BACKENDS and backend != "cpu":
            # CPU runs the kernel interpreted (the test path); any other
            # backend would silently interpret too — orders of magnitude
            # slower than the XLA path, the opposite of what opting in
            # asks for.
            raise ValueError(
                f"pfb_kernel={pfb_kernel!r} is not supported on backend "
                f"{backend!r} (TPU compiles it; CPU interprets for tests)"
            )
        if pfb_kernel == "fused1":
            if resolved != "matmul":
                raise ValueError(
                    "pfb_kernel='fused1' fuses the matmul-DFT's first "
                    "stage; it needs fft_method='matmul'"
                )
            if len(dftmod.default_factors(nfft)) < 2:
                raise ValueError(
                    "pfb_kernel='fused1' needs a multi-factor nfft "
                    f"(> {dftmod.DIRECT_DFT_MAX})"
                )
            if twisted:
                raise ValueError(
                    "pfb_kernel='fused1' emits natural order; it does not "
                    "combine with dft_order='twisted'"
                )
    use_pallas_pfb = pfb_kernel == "pallas"
    use_fused1 = pfb_kernel == "fused1"
    interp = backend not in _MATMUL_ONLY_BACKENDS

    # Tail/detect kernel resolution.  Three pallas surfaces cover the
    # pipeline after the fused1 front (each measured on the chip,
    # DESIGN.md §9):
    #
    # - COMBINED tail+detect (blit/ops/pallas_detect.tail2_detect,
    #   ``use_td``): DFT levels 2+3, the inner untwist, the detection
    #   product (any detect_stokes_planar product — the pol pair is
    #   block-resident), and (up to one XLA lane swap) the product
    #   transpose in ONE pass — the bf16 tail spectra never exist in HBM.
    #   Interleaved A/B at the production config: 15.1-16.7 vs
    #   9.9-11.0 GB/s (+48%) — "auto" prefers it whenever eligible.
    # - tail-only (blit/ops/pallas_dft.dft_tail2, ``use_pallas_tail``):
    #   levels 2+3 + inner untwist, XLA detect.  A/B: +15% over the XLA
    #   tail — the fallback when the combined kernel's output planes
    #   exceed VMEM.
    # - detect-only (blit/ops/pallas_detect.detect_untwist_i,
    #   ``use_pallas_detect``): twisted XLA tail, fused detect+untwist.
    #   A/B: parity — a verified-correct opt-in tuning surface.
    if detect_kernel not in ("auto", "xla", "pallas"):
        raise ValueError(f"bad detect_kernel {detect_kernel!r}")
    if tail_kernel not in ("auto", "xla", "pallas"):
        raise ValueError(f"bad tail_kernel {tail_kernel!r}")
    detect_eligible = td_eligible = tail_eligible = False
    if use_fused1:
        from blit.ops import pallas_detect
        from blit.ops.pallas_dft import tail2_fits

        _kw = dict(
            npol=voltages.shape[2],
            esize=2 if dtype == "bfloat16" else 4,
        )
        _factors = dftmod.default_factors(nfft)
        # detect_untwist_i is Stokes-I only; tail2_detect covers every
        # detect_stokes_planar product (the pol pair is block-resident).
        detect_eligible = stokes == "I" and pallas_detect.fits(
            _factors, **_kw)
        td_eligible = pallas_detect.tail2_detect_fits(
            _factors, stokes=stokes, **_kw)
        _nframes = voltages.shape[1] // nfft - ntap + 1
        tail_eligible = (
            len(_factors) == 3
            and tail2_fits(
                voltages.shape[0] * voltages.shape[2] * _nframes
                * _factors[0],
                _factors[1], _factors[2], dtype,
            )
        )

    use_td = (
        td_eligible and detect_kernel != "xla" and tail_kernel != "xla"
    )
    if detect_kernel == "pallas" and tail_kernel == "pallas" and not use_td:
        raise ValueError(
            "tail_kernel='pallas' with detect_kernel='pallas' (the fused "
            "tail+detect) needs pfb_kernel='fused1', a known stokes "
            "product, exactly 3 DFT factors, and the nif output planes "
            "inside the VMEM budget"
        )
    use_pallas_detect = (
        not use_td and detect_kernel == "pallas" and detect_eligible
    )
    if detect_kernel == "pallas" and not (use_td or use_pallas_detect):
        raise ValueError(
            "detect_kernel='pallas' (without tail_kernel='pallas') needs "
            "pfb_kernel='fused1', stokes='I', <= 3 DFT factors, and "
            "factor sizes inside the VMEM budget"
        )
    use_pallas_tail = (
        not use_td and not use_pallas_detect
        and tail_kernel != "xla" and tail_eligible
    )
    if tail_kernel == "pallas" and not (use_td or use_pallas_tail):
        raise ValueError(
            "tail_kernel='pallas' needs pfb_kernel='fused1', exactly 3 "
            "DFT factors, and panel sizes inside the VMEM budget"
        )

    # Record what "auto" resolved to — 'auto' silently upgraded to the
    # fused kernels in round 3, so output diffs against older runs must be
    # attributable (ADVICE r3).  Trace-time only: a jit cache hit does not
    # re-run this body, so the record describes the most recent TRACE
    # (bench.py surfaces it in its JSON metadata).
    _LAST_PLAN.clear()
    _LAST_PLAN.update(
        fft_method=resolved,
        pfb_kernel=pfb_kernel,
        tail_kernel=("tail2_detect" if use_td
                     else "dft_tail2" if use_pallas_tail else "xla"),
        detect_kernel=("tail2_detect" if use_td
                       else "detect_untwist_i" if use_pallas_detect
                       else "xla"),
        dft_order="twisted" if twisted else "natural",
        dtype=dtype,
    )

    def core(v):
        if use_fused1:
            # dequant + PFB + DFT stage 1 in one pallas pass; the frame
            # planes never hit HBM.  Remaining factors + natural-order
            # assembly via dft_tail, then detect as usual.
            from blit.ops.pallas_pfb import pfb_dft1

            factors = dftmod.default_factors(nfft)
            n1 = factors[0]
            w1r, w1i = (jnp.asarray(a)
                        for a in dftmod.dft_matrices(n1, "float32"))
            t1r, t1i = (jnp.asarray(a)
                        for a in dftmod.twiddles(n1, nfft // n1, "float32"))
            ur, ui = pfb_dft1(
                v, shifted_coeffs, w1r, w1i, t1r, t1i, dtype=dtype,
                interpret=interp,
            )
            if use_td:
                from blit.ops.pallas_detect import tail2_detect

                # Whole remaining pipeline — tail levels, untwist, detect,
                # product transpose — in one pass; power arrives frame-
                # major in the product layout.
                power = tail2_detect(
                    ur, ui, factors[1], factors[2], stokes=stokes,
                    interpret=interp,
                )  # (nframes, nif, cb, nfft)
                if nint > 1:
                    if power.shape[0] % nint:
                        raise ValueError(
                            f"integrate: nint={nint} does not divide "
                            f"nframes={power.shape[0]}"
                        )
                    power = power.reshape(
                        (power.shape[0] // nint, nint) + power.shape[1:]
                    ).sum(axis=1)
                return power  # (ntime_out, nif, cb, nfft)
            if use_pallas_detect:
                from blit.ops.pallas_detect import detect_untwist_i

                # Remaining factors in twisted order (no transposes);
                # the detect kernel untwists while it detects.
                vr, vi = dftmod.dft_tail(
                    ur, ui, factors, precision=prec, dtype=dtype,
                    order="twisted",
                )
                power = detect_untwist_i(vr, vi, factors, interpret=interp)
                # (cb, frames, nfft) → (cb, nif=1, t, nfft)
                return integrate(power, nint)[:, None]
            if use_pallas_tail:
                from blit.ops.pallas_dft import dft_tail2

                # Fused levels 2+3 (+ inner untwist) → natural-m panels;
                # only the level-0 untwist remains.
                vr, vi = dft_tail2(
                    ur, ui, factors[1], factors[2], dtype=dtype,
                    interpret=interp,
                )
                bshape = ur.shape[:3]
                sr = jnp.swapaxes(vr, -1, -2).reshape(bshape + (nfft,))
                si = jnp.swapaxes(vi, -1, -2).reshape(bshape + (nfft,))
            else:
                sr, si = dftmod.dft_tail(
                    ur, ui, factors, precision=prec, dtype=dtype
                )
            if sr.dtype != jnp.float32:
                sr, si = sr.astype(jnp.float32), si.astype(jnp.float32)
            power = detect_stokes_planar(sr, si, stokes)
            return integrate(power, nint)
        if use_pallas_pfb:
            from blit.ops.pallas_pfb import pfb_dequant

            fr, fi = pfb_dequant(
                v, shifted_coeffs, dtype=dtype, interpret=interp,
            )
        else:
            re, im = dequantize(v, dtype=work_dtype)  # (cb, ntime, npol)
            re = jnp.moveaxis(re, -1, 1)  # (cb, npol, ntime)
            im = jnp.moveaxis(im, -1, 1)
            fr = pfb_frontend(re, wcoeffs)  # (cb, npol, nframes, nfft)
            fi = pfb_frontend(im, wcoeffs)
        sr, si = fft_planar(
            fr, fi, method=fft_method, precision=prec, dtype=dtype,
            order="twisted" if twisted else "natural",
        )
        if sr.dtype != jnp.float32:
            # Detect + integrate accumulate in f32 (the cast fuses into the
            # detect kernel; only the DFT intermediates stay half-width).
            sr, si = sr.astype(jnp.float32), si.astype(jnp.float32)
        power = detect_stokes_planar(sr, si, stokes)  # (cb, nif, frames, nfft)
        power = integrate(power, nint)  # (cb, nif, ntime_out, nfft)
        if twisted:
            power = dftmod.untwist(power, dftmod.default_factors(nfft))
        return power

    if channel_block and channel_block < nchan:
        if nchan % channel_block:
            raise ValueError(
                f"channel_block={channel_block} does not divide nchan={nchan}"
            )
        groups = voltages.reshape(
            (nchan // channel_block, channel_block) + voltages.shape[1:]
        )
        power = jax.lax.map(core, groups)
        if use_td:
            # (g, t, nif, cb, nfft): channel-major assembly — one
            # transpose of the (already detected) power, the blocked
            # mode's price.
            power = jnp.moveaxis(power, 0, 2)  # (t, nif, g, cb, nfft)
        else:
            power = power.reshape((nchan,) + power.shape[2:])
    else:
        power = core(voltages)
    if use_td:
        # core's fused tail+detect already emitted the product layout
        # (t, nif, [g,] cb, nfft); flatten the channel axes into place.
        out = power.reshape(power.shape[0], power.shape[1], nchan * nfft)
    else:
        # → (ntime_out, nif, nchan*nfft), channel fastest.
        out = jnp.transpose(power, (2, 1, 0, 3))
        out = out.reshape(out.shape[0], out.shape[1], nchan * nfft)
    if fqav_by > 1:
        out = _fqav(out, fqav_by)
    return out


def channelize_blocked(
    voltages,
    coeffs,
    *,
    channel_block: int,
    **kw,
) -> jax.Array:
    """Host-looped channel blocking: the compile-friendly replacement for
    ``channelize(channel_block=)``'s in-jit ``lax.map`` (whose XLA loop
    blows compile time past 500 s at nfft=2^20, DESIGN.md §3/§9).

    Dispatches :func:`channelize` once per ``channel_block``-sized group of
    coarse channels — ONE jit compile (group shape is constant), dispatches
    enqueued async back-to-back, device-side concatenation of the per-group
    products.  Peak HBM is bounded by one group's intermediates plus the
    final product, so the per-*call* net work can grow well past what the
    flat layout fits (the dispatch-amortization lever of DESIGN.md §3 at
    bounded memory, now at seconds-scale compile).

    Same result as ``channelize(..., channel_block=0)`` (golden-tested).
    """
    nchan = voltages.shape[0]
    if channel_block <= 0 or channel_block >= nchan:
        return channelize(voltages, coeffs, **kw)
    if nchan % channel_block:
        raise ValueError(
            f"channel_block={channel_block} does not divide nchan={nchan}"
        )
    outs = [
        channelize(voltages[c : c + channel_block], coeffs, **kw)
        for c in range(0, nchan, channel_block)
    ]
    return jnp.concatenate(outs, axis=-1)


def channelize_np(
    voltages: np.ndarray,
    coeffs: np.ndarray,
    *,
    nfft: int,
    ntap: int = 4,
    nint: int = 1,
    stokes: str = "I",
) -> np.ndarray:
    """NumPy golden-reference implementation of :func:`channelize` (tests)."""
    v = voltages.astype(np.float32)
    z = v[..., 0] + 1j * v[..., 1]  # (nchan, ntime, npol)
    z = np.moveaxis(z, -1, 1)  # (nchan, npol, ntime)
    nchan, npol, ntime = z.shape
    nblk = ntime // nfft
    nframes = nblk - ntap + 1
    blocks = z.reshape(nchan, npol, nblk, nfft)
    frames = np.zeros((nchan, npol, nframes, nfft), dtype=np.complex64)
    for k in range(ntap):
        frames += coeffs[k] * blocks[:, :, k : k + nframes, :]
    spec = np.fft.fftshift(np.fft.fft(frames, axis=-1), axes=-1)
    xs, ys = (spec[:, 0], spec[:, 1]) if npol == 2 else (spec[:, 0], spec[:, 0])
    xx = (xs.real**2 + xs.imag**2).astype(np.float32)
    yy = (ys.real**2 + ys.imag**2).astype(np.float32)
    if stokes == "I":
        prods = [xx + yy] if npol == 2 else [xx]
    elif stokes == "XX":
        prods = [xx]
    elif stokes == "YY":
        prods = [yy]
    elif stokes == "XXYY":
        prods = [xx, yy]
    elif stokes in ("full", "IQUV"):
        xy = xs * np.conj(ys)
        if stokes == "full":
            prods = [xx, yy, xy.real.astype(np.float32), xy.imag.astype(np.float32)]
        else:
            prods = [
                xx + yy,
                xx - yy,
                (2 * xy.real).astype(np.float32),
                (-2 * xy.imag).astype(np.float32),
            ]
    else:
        raise ValueError(stokes)
    power = np.stack(prods, axis=1)  # (nchan, nif, nframes, nfft)
    if nint > 1:
        power = power.reshape(
            nchan, power.shape[1], nframes // nint, nint, nfft
        ).sum(axis=3)
    out = np.transpose(power, (2, 1, 0, 3))
    return np.ascontiguousarray(out.reshape(out.shape[0], out.shape[1], nchan * nfft))


def output_header(
    raw_header: dict,
    *,
    nfft: int,
    nint: int,
    stokes: str = "I",
) -> dict:
    """Filterbank header for the channelized product, derived from a GUPPI
    RAW block header (rawspec-equivalent metadata path).

    Frequency mapping: coarse channel c (of OBSNCHAN, center frequencies
    spanning OBSBW around OBSFREQ) yields nfft fine channels, fftshifted so
    fine index f maps to offset ``(f - nfft/2) * chan_bw/nfft`` from the
    coarse center.  With the GBT convention OBSBW < 0, channel 0 is the
    highest frequency and ``foff`` is negative (SURVEY.md §0).
    """
    obsnchan = int(raw_header["OBSNCHAN"])
    obsfreq = float(raw_header["OBSFREQ"])
    obsbw = float(raw_header["OBSBW"])
    tbin = float(raw_header.get("TBIN", 0.0) or 0.0)
    chan_bw = obsbw / obsnchan
    foff = chan_bw / nfft
    # Center frequency of coarse channel 0:
    c0 = obsfreq - obsbw / 2 + chan_bw / 2
    # Fine channel 0 of coarse 0 sits nfft/2 fine-widths below its center:
    fch1 = c0 - (nfft / 2) * foff
    return {
        "fch1": fch1,
        "foff": foff,
        "nchans": obsnchan * nfft,
        "nifs": STOKES_NIF[stokes],
        "tsamp": tbin * nfft * nint,
        "nbits": 32,
        "nfpc": nfft,
        "source_name": raw_header.get("SRC_NAME", ""),
        "tstart": _raw_tstart_mjd(raw_header),
    }


def _raw_tstart_mjd(hdr: dict) -> float:
    imjd = float(hdr.get("STT_IMJD", 0))
    smjd = float(hdr.get("STT_SMJD", 0))
    offs = float(hdr.get("STT_OFFS", 0))
    return imjd + (smjd + offs) / 86400.0
