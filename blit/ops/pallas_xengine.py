"""VMEM-resident FX-correlator X-engine (Pallas, packed visibility layout).

The un-parking of DESIGN.md §9's round-4 decision ("pallas X-engine parked
until a real workload's nant makes the tiles MXU-sized"): at the repo's own
array scale of 64 antennas (bench.py beamform leg) the per-(chan, fine)
baseline matmul is (nant·npol)² = 128² — exactly MXU-sized — and the
measured whole-correlate rates at that shape justify the kernel
(interleaved A/B on the chip, tools/ab_fx64_pallas.py, nant=64 nchan=16
nfft=512 nblk=64):

    einsum X-engine            21.1 GB/s input (median)
    pallas ft=8 (this kernel)  25.1 GB/s  (+19%)
    pallas ft=16               24.4 GB/s
    pallas ft=32               VMEM OOM (19.8 MB scoped > 16 MB)

XLA-level alternatives measured first and at parity (tools/ab_fx64.py:
packed-layout einsums 0.996x, bf16-cast operands 0.996x), so the win here
is genuinely the single-pass VMEM residency: per grid step both planes'
``(ft, nap, nframes)`` spectra blocks are loaded once and all four real
products run as batched ``dot_general``s without re-touching HBM — the
4-einsum path reads the spectra planes once per product pair.

Layout: the kernel emits visibilities PACKED as ``(nchan, nfft, ap, bq)``
(``ap`` = antenna-major antenna·pol).  Transposing to the standard
``(a, b, c, f, p, q)`` layout would move 2×vis-size bytes and eat the win,
so the packed layout is an opt-in output format of
:func:`blit.parallel.correlator.correlate` — integrations and most
downstream reductions are layout-indifferent.

Eligibility: ``nap >= 128`` (MXU-sized tiles — below that the einsum path
measures faster: 49 GB/s X-engine stage at nap=16 vs the kernel's win
shape) and ``nfft % ft == 0``.  Off-TPU the caller falls back to packed
einsums (same layout, golden-identical); ``interpret=True`` exists for
unit tests.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from blit.ops.dft import Planar

FT_DEFAULT = 8

# Scoped-VMEM model for eligibility: block bytes double-buffer, and the
# compiler's scoped allocation runs ~1.6x the naive block arithmetic
# (measured: ft=32 at nframes=61 is 12.4 MB naive but OOM'd at 19.8 MB
# against the 16 MB limit).  The factor carries margin on top of the
# measurement so admitted shapes sit clearly inside the limit.
_VMEM_LIMIT = 16 << 20
_SCOPED_FACTOR = 1.7


def eligible(
    nap: int,
    nfft: int,
    nframes: int,
    ft: int = FT_DEFAULT,
    itemsize: int = 4,
) -> bool:
    """Shapes where the kernel measured faster than the einsum X-engine
    AND fits scoped VMEM (long time segments grow the input blocks
    linearly with ``nframes`` — those fall back to the einsum path
    instead of compile-failing, the channelize.py fits() convention).

    ``itemsize`` is the SPECTRA element size: bf16-staged spectra halve
    the input blocks, so longer segments stay eligible than with f32.
    Outputs always accumulate f32.
    """
    in_bytes = 2 * (ft * nap * nframes) * itemsize
    out_bytes = 2 * (ft * nap * nap) * 4
    scoped = (in_bytes + out_bytes) * 2 * _SCOPED_FACTOR
    return (
        nap >= 128
        and nap % 8 == 0
        and nfft % ft == 0
        and scoped <= _VMEM_LIMIT
    )


def pick_ft(
    nap: int, nfft: int, nframes: int, itemsize: int = 4
) -> Optional[int]:
    """Largest fine tile in {8, 4} that divides ``nfft`` and fits the
    VMEM model, or None (→ einsum path).  ft=8 measured best at nap=128
    (25.1 vs ft=16's 24.4 GB/s); larger nap or longer segments shrink
    the tile one halving instead of falling off the kernel entirely.
    Tiles below 4 are unmeasured territory — those shapes take the
    einsum path rather than extrapolate."""
    for ft in (FT_DEFAULT, 4):
        if eligible(nap, nfft, nframes, ft=ft, itemsize=itemsize):
            return ft
    return None


def _kernel(ar_ref, ai_ref, vr_ref, vi_ref):
    ar = ar_ref[0]  # (ft, nap, nframes)
    ai = ai_ref[0]
    # Contract frames, batch fine channels: (ft, nap, nap) per product.
    # f32 accumulation regardless of operand dtype (bf16 spectra halve
    # the kernel's reads and VMEM blocks; the MXU multiplies at bf16
    # precision either way — the TPU's default matmul precision).
    dn = (((2,), (2,)), ((0,), (0,)))
    kw = dict(preferred_element_type=jnp.float32)
    rr = jax.lax.dot_general(ar, ar, dn, **kw)
    ii = jax.lax.dot_general(ai, ai, dn, **kw)
    ir = jax.lax.dot_general(ai, ar, dn, **kw)
    ri = jax.lax.dot_general(ar, ai, dn, **kw)
    vr_ref[0] = rr + ii
    vi_ref[0] = ir - ri


@functools.partial(jax.jit, static_argnames=("ft", "interpret"))
def xengine_packed(
    sr: jax.Array,
    si: jax.Array,
    *,
    ft: int = FT_DEFAULT,
    interpret: bool = False,
) -> Planar:
    """Cross-multiply + time-integrate planar spectra, packed output.

    ``s``: (nant, nchan, npol, nframes, nfft) planar pair →
    visibilities ``(nchan, nfft, nap, nap)`` as an f32 (re, im) pair with
    ``V[c, f, ap, bq] = Σ_t S_a S_b*`` (``ap`` antenna-major).  One XLA
    transpose packs the spectra to ``(nchan, nfft, nap, nframes)``; the
    kernel then reads every spectra byte exactly once.
    """
    nant, nchan, npol, nframes, nfft = sr.shape
    nap = nant * npol
    if nfft % ft:
        raise ValueError(f"nfft={nfft} must divide into fine tiles of {ft}")

    def pack(s):
        return jnp.transpose(s, (1, 4, 0, 2, 3)).reshape(
            nchan, nfft, nap, nframes
        )

    spec_in = pl.BlockSpec((1, ft, nap, nframes), lambda c, f: (c, f, 0, 0))
    spec_out = pl.BlockSpec((1, ft, nap, nap), lambda c, f: (c, f, 0, 0))
    return pl.pallas_call(
        _kernel,
        grid=(nchan, nfft // ft),
        in_specs=[spec_in, spec_in],
        out_specs=[spec_out, spec_out],
        out_shape=[
            jax.ShapeDtypeStruct((nchan, nfft, nap, nap), jnp.float32),
            jax.ShapeDtypeStruct((nchan, nfft, nap, nap), jnp.float32),
        ],
        interpret=interpret,
    )(pack(sr), pack(si))
