"""End-to-end data-integrity plane (ISSUE 13 tentpole).

blit can *inject* corruption (the ``corrupt`` fault mode bit-flips
delivered GUPPI frames) but until this module it could not *detect*
any: serve-cache fingerprints hashed ``(path, size, mtime_ns)``
metadata, every "crash-corrupted?" resume probe was a byte-length
check, and blit/io/sigproc.py's slab guard documented the gap out
loud ("a valid-looking corrupt product nothing downstream can
detect").  For a multi-petabyte archive lifecycle (Lebofsky+ 2019,
arXiv:1906.07391) silent bit-rot and torn-but-plausible state are the
last unguarded failure class — this module closes it with three
digest surfaces, all stdlib ``zlib.crc32`` (CRC32C-style streaming
checksums; cryptographic strength is not the threat model, bit-rot
and torn writes are):

- **Ingest digests** — an optional ``<member>.digests.json`` sidecar
  carries one CRC per RAW block (over the on-disk payload bytes).
  When present, :class:`blit.io.guppi.GuppiRaw` verifies every block
  it delivers (the on-disk bytes against the sidecar at first touch,
  the delivered frame against the on-disk bytes per delivery — so
  both disk rot and an in-flight flip are caught) and a mismatched
  block is zero-filled — the PR 2/7 zero-weight mask discipline
  (:func:`blit.parallel.antenna.record_mask`) applied to blocks — so
  the product is byte-identical to a reduction of the same recording
  with that block zeroed, never garbage.  ``integrity.bad_block``
  counts it, the flight recorder dumps the incident.

- **Product manifests** — every ``.fil``/``.h5``/``.hits`` writer
  (sync, async, resumable, sharded, stream — they all go through the
  writer classes in blit/io/* and blit/pipeline.py) publishes a
  ``<product>.manifest.json`` sidecar: per-window content digests (a
  claim ledger, the resumable writers checkpoint it beside the
  cursor), the whole-file CRC on completion, and writer provenance.
  Resume paths verify the *claimed region's digest* before trusting a
  cursor (upgrading the length-only torn-write probes in
  ``resume_fil_ok`` / ``resume_target_ok`` / the hits byte-offset
  check), and the serve disk tier verifies entry content on load.
  Digesting rides the threads that already own the bytes (the
  write-behind sink thread folds each slab as it appends), so the
  ingest bench stays within its noise band.

- **Operator surface** — :func:`fsck` walks a tree verifying
  manifests and cache entries, quarantining mismatches into a
  ``.quarantine/`` sibling (``--repair`` re-derives quarantined cache
  entries: fingerprints are content-addressed recipes, and the meta
  sidecar records the recipe); :class:`Scrubber` samples disk-tier
  entries in the background under a bytes/s budget
  (``BLIT_SCRUB_*`` / SiteConfig opt-in), publishing
  ``integrity.scrub.*`` counters and the ``integrity.verify_s``
  histogram through the PR 10 monitor plane; and ``/healthz`` reports
  ``degraded`` while any watched quarantine is non-empty
  (:func:`quarantine_health`).

Import discipline: stdlib + numpy at module scope, every blit import
lazy inside the function that needs it — the I/O layer (guppi,
sigproc, fbh5, hits) calls up into this module, and this module calls
back down only at verification time.
"""

from __future__ import annotations

import json
import logging
import os
import shutil
import socket
import threading
import time
import zlib
from typing import Dict, List, Optional, Tuple

import numpy as np

log = logging.getLogger("blit.integrity")

MANIFEST_KIND = "blit.manifest"
MANIFEST_VERSION = 1
MANIFEST_SUFFIX = ".manifest.json"

DIGESTS_KIND = "blit.digests"
DIGESTS_VERSION = 1
DIGESTS_SUFFIX = ".digests.json"

QUARANTINE_DIR = ".quarantine"

# Claim-ledger bound (the blit.io.hits.CLAIM_LEDGER_MAX discipline):
# every resumable append re-serializes the manifest, so the ledger must
# not grow with session length.  Claims older than the trimmed tail
# verify through the newest surviving earlier entry (prefix coverage).
LEDGER_MAX = 4096

# Chunk size for streaming file CRCs (bounded memory over TB products).
_CRC_CHUNK = 8 << 20

# Product extensions fsck recognizes when counting unmanifested files.
_PRODUCT_EXTS = (".fil", ".h5", ".hdf5", ".hits")


class IntegrityError(ValueError):
    """A malformed/corrupt integrity sidecar (digests file that does not
    parse, wrong kind, ...) — loud by design: reducing against a sidecar
    that cannot be trusted silently would defeat the whole plane."""


# -- crc helpers -------------------------------------------------------------


def crc32_update(crc: int, buf) -> int:
    """Fold ``buf`` (any C-contiguous buffer: bytes, int8 ndarray, a
    memmap slice) into a running CRC32."""
    return zlib.crc32(buf, crc) & 0xFFFFFFFF


def crc32_file(path: str, start: int = 0, length: Optional[int] = None,
               crc: int = 0) -> int:
    """Streaming CRC32 over ``path[start : start+length)`` (to EOF when
    ``length`` is None) at bounded memory."""
    with open(path, "rb") as f:
        f.seek(start)
        remaining = length
        while True:
            take = _CRC_CHUNK if remaining is None else min(
                _CRC_CHUNK, remaining)
            if take <= 0:
                break
            chunk = f.read(take)
            if not chunk:
                if remaining is not None:
                    raise IntegrityError(
                        f"{path}: EOF {remaining} bytes before the end of "
                        "the digested region")
                break
            crc = crc32_update(crc, chunk)
            if remaining is not None:
                remaining -= len(chunk)
    return crc


def hex_crc(crc: int) -> str:
    return f"{crc & 0xFFFFFFFF:08x}"


def parse_crc(s) -> Optional[int]:
    try:
        return int(str(s), 16) & 0xFFFFFFFF
    except (TypeError, ValueError):
        return None


def _atomic_json(path: str, doc: Dict) -> None:
    """The sidecar publish rule (the ReductionCursor.save discipline):
    write-temp, fsync, ``os.replace`` — a reader sees a whole sidecar or
    none, never a torn one."""
    tmp = f"{path}.tmp.{os.getpid()}.{threading.get_ident()}"
    with open(tmp, "w") as f:
        json.dump(doc, f)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


# -- counters / telemetry ----------------------------------------------------


def incr(name: str, n: int = 1) -> None:
    """Bump a process-wide ``integrity.*`` counter: rides
    :func:`blit.faults.incr`, so it lands in ``faults.counters()``, the
    flight-recorder event ring, ``Timeline.report(include_faults=True)``,
    ``blit_fault_total`` on ``/metrics`` and the ``blit top`` fault rows
    — the whole PR 10 monitor plane, for free."""
    from blit import faults

    faults.incr(name, n)


def observe_verify(seconds: float, timeline=None) -> None:
    """Record one verification pass into the ``integrity.verify_s``
    histogram (process-wide, plus the caller's timeline when given)."""
    try:
        from blit.observability import process_timeline

        process_timeline().observe("integrity.verify_s", seconds)
        if timeline is not None:
            timeline.observe("integrity.verify_s", seconds)
    except Exception:  # noqa: BLE001 — telemetry must not fail verification
        pass


def ingest_verify_enabled() -> bool:
    """Honor RAW digest sidecars?  On by default; ``BLIT_VERIFY_INGEST=0``
    is the drill/bench escape hatch (a sidecar only costs anything when
    it exists next to the recording)."""
    return os.environ.get("BLIT_VERIFY_INGEST", "1") not in (
        "0", "false", "False")


def cache_verify_enabled() -> bool:
    """Content-verify serve disk-tier loads?  On by default;
    ``BLIT_VERIFY_CACHE=0`` restores the structural-probe-only loads."""
    return os.environ.get("BLIT_VERIFY_CACHE", "1") not in (
        "0", "false", "False")


# -- RAW digest sidecars -----------------------------------------------------


def raw_digests_path(member: str) -> str:
    return member + DIGESTS_SUFFIX


def _iter_block_crcs(member: str):
    """Yield ``(index, crc)`` over a RAW member's whole on-disk blocks —
    the ONE block walk the sidecar writer and the fsck verifier share,
    so what a "block's bytes" means can never drift between them.
    Truncated trailing blocks are skipped exactly as GuppiRaw skips
    them; the file is read directly (never through the ``guppi.read``
    injection point — digests describe the bytes on disk, not a
    drilled delivery)."""
    from blit.io.guppi import read_raw_header

    with open(member, "rb") as f:
        size = os.path.getsize(member)
        i = 0
        while True:
            try:
                hdr, off = read_raw_header(f)
            except EOFError:
                break
            blocsize = int(hdr["BLOCSIZE"])
            if off + blocsize > size:
                break
            crc = 0
            remaining = blocsize
            while remaining:
                chunk = f.read(min(_CRC_CHUNK, remaining))
                if not chunk:
                    raise IntegrityError(f"{member}: short read mid-block")
                crc = crc32_update(crc, chunk)
                remaining -= len(chunk)
            yield i, crc
            i += 1


def write_raw_digests(member: str) -> str:
    """Compute and atomically publish the per-block digest sidecar of one
    RAW member: one CRC32 per block over its on-disk payload bytes
    (``[data_offset, data_offset + BLOCSIZE)``)."""
    blocks = [hex_crc(crc) for _i, crc in _iter_block_crcs(member)]
    path = raw_digests_path(member)
    _atomic_json(path, {
        "kind": DIGESTS_KIND, "version": DIGESTS_VERSION, "algo": "crc32",
        "member": os.path.basename(member), "blocks": blocks,
    })
    return path


def load_raw_digests(member: str) -> Optional[List[int]]:
    """Parse a member's digest sidecar → per-block CRC list, or None when
    absent.  A sidecar that EXISTS but does not parse raises
    :class:`IntegrityError` — never reduce against an untrustworthy
    sidecar silently."""
    path = raw_digests_path(member)
    if not os.path.exists(path):
        return None
    try:
        with open(path) as f:
            doc = json.load(f)
        if doc.get("kind") != DIGESTS_KIND:
            raise ValueError(f"kind={doc.get('kind')!r}")
        out = []
        for s in doc["blocks"]:
            crc = parse_crc(s)
            if crc is None:
                raise ValueError(f"bad digest {s!r}")
            out.append(crc)
        return out
    except (OSError, ValueError, KeyError, TypeError) as e:
        raise IntegrityError(
            f"{path}: malformed RAW digest sidecar ({e}); remove or "
            "regenerate it (blit.integrity.write_raw_digests)") from e


def verify_raw_member(member: str) -> List[str]:
    """Re-derive a RAW member's per-block digests against its sidecar →
    problem strings (empty = verified).  The fsck leg for the archive
    side: a rotten block is REPORTED here (and zero-masked at ingest by
    GuppiRaw) but never quarantined — RAW members are the read-only
    source of truth, moving them is an operator decision."""
    try:
        digests = load_raw_digests(member)
    except IntegrityError as e:
        return [str(e)]
    if digests is None:
        return []
    problems: List[str] = []
    blocks = 0
    try:
        for i, crc in _iter_block_crcs(member):
            blocks = i + 1
            if i < len(digests) and crc != digests[i]:
                problems.append(
                    f"block {i} digest mismatch ({hex_crc(crc)} != "
                    f"{hex_crc(digests[i])})")
        if blocks < len(digests):
            problems.append(
                f"member holds {blocks} whole blocks, sidecar digests "
                f"{len(digests)} (truncated since digesting?)")
    except (OSError, IntegrityError) as e:
        problems.append(f"unreadable member: {e}")
    if problems:
        incr("integrity.bad_block", len(problems))
    return problems


# -- product manifests -------------------------------------------------------


def manifest_path(product: str) -> str:
    return product + MANIFEST_SUFFIX


class ManifestWriter:
    """The per-writer manifest accumulator: a running content CRC, a
    bounded per-window claim ledger, and the atomic sidecar publish.

    CRC space is per format: ``fil`` and ``hits`` fold the FILE bytes in
    write order (header first), so the running CRC at any claim equals
    ``crc32_file(path, 0, nbytes)`` and the completed running CRC *is*
    the whole-file CRC; ``fbh5`` folds the LOGICAL dataset rows (libhdf5
    metadata churn makes file-byte space meaningless mid-stream) and the
    whole-file CRC is computed by one re-read at close
    (``publish(scan_file=True)``).

    Ledger entries are ``[rows, nbytes, crc-hex]`` — rows claimed, bytes
    folded so far, running CRC — and :func:`verify_claim` replays them.
    ``save`` is best-effort (a failing manifest write must never fail the
    product it describes); the counters say when it happened.
    """

    def __init__(self, final_path: str, fmt: str, *, data_offset: int = 0,
                 row_bytes: int = 0, fingerprint: Optional[str] = None,
                 writer: str = ""):
        self.final_path = final_path
        self.fmt = fmt
        self.data_offset = data_offset
        self.row_bytes = row_bytes
        self.fingerprint = fingerprint
        self.writer = writer
        self.crc = 0
        self.nbytes = 0
        self.rows = 0
        self.ledger: List[List] = []

    # -- accumulation ------------------------------------------------------
    def fold(self, buf) -> None:
        """Fold appended content (bytes / contiguous ndarray)."""
        self.crc = crc32_update(self.crc, buf)
        self.nbytes += memoryview(buf).nbytes

    def fold_path(self, path: str, length: Optional[int] = None) -> None:
        """Fold existing file bytes (header prologue; resume rebuild)."""
        n = os.path.getsize(path) if length is None else length
        self.crc = crc32_file(path, 0, n, self.crc)
        self.nbytes += n

    def claim(self, rows: int) -> None:
        """Record a durable claim at ``rows`` with the current CRC."""
        self.rows = rows
        self.ledger.append([int(rows), int(self.nbytes),
                            hex_crc(self.crc)])
        del self.ledger[:-LEDGER_MAX]

    # -- publish -----------------------------------------------------------
    def _doc(self, complete: bool, file_bytes: Optional[int],
             file_crc: Optional[int]) -> Dict:
        return {
            "kind": MANIFEST_KIND, "version": MANIFEST_VERSION,
            "product": os.path.basename(self.final_path),
            "format": self.fmt,
            "complete": bool(complete),
            "rows": int(self.rows),
            "data_offset": int(self.data_offset),
            "row_bytes": int(self.row_bytes),
            "data_crc32": hex_crc(self.crc),
            "bytes": file_bytes,
            "crc32": hex_crc(file_crc) if file_crc is not None else None,
            "windows": list(self.ledger),
            "fingerprint": self.fingerprint,
            "writer": {"writer": self.writer,
                       "host": socket.gethostname(), "pid": os.getpid(),
                       "t": time.time()},
        }

    def save(self, complete: bool = False,
             file_bytes: Optional[int] = None,
             file_crc: Optional[int] = None) -> bool:
        """Atomically (re)publish the sidecar; best-effort (returns
        whether it landed — products must not fail on manifest I/O)."""
        try:
            _atomic_json(manifest_path(self.final_path),
                         self._doc(complete, file_bytes, file_crc))
            return True
        except OSError:
            incr("integrity.manifest.error")
            log.warning("manifest publish of %s failed",
                        self.final_path, exc_info=True)
            return False

    def publish(self, scan_file: bool = False) -> bool:
        """Publish the COMPLETE manifest for the finished product at
        ``final_path``.  ``scan_file=True`` re-reads the file for the
        whole-file CRC (the fbh5 path — its running CRC is logical);
        otherwise the running CRC is the file CRC (fil/hits)."""
        try:
            size = os.path.getsize(self.final_path)
            crc = (crc32_file(self.final_path) if scan_file else self.crc)
        except OSError:
            incr("integrity.manifest.error")
            log.warning("manifest publish of %s failed",
                        self.final_path, exc_info=True)
            return False
        return self.save(complete=True, file_bytes=size, file_crc=crc)


def try_load_manifest(product: str
                      ) -> Tuple[Optional[Dict], Optional[str]]:
    """``(doc, problem)`` for a product's manifest: ``(None, None)`` when
    absent, ``(None, "why")`` when present but unusable (torn JSON,
    wrong kind — fail closed, never trust), ``(doc, None)`` when it
    parses."""
    path = manifest_path(product)
    if not os.path.exists(path):
        return None, None
    try:
        with open(path) as f:
            doc = json.load(f)
        if not isinstance(doc, dict) or doc.get("kind") != MANIFEST_KIND:
            return None, f"not a {MANIFEST_KIND} document"
        return doc, None
    except (OSError, ValueError) as e:
        return None, f"unreadable/torn manifest: {e}"


def _ledger_entry(doc: Dict, rows: int) -> Optional[List]:
    """The EXACT ledger entry for a claim of ``rows``.  Exact, not
    at-or-before: the writers checkpoint the manifest between the data
    fsync and the cursor save, so every row count a cursor can legally
    claim has an entry — a missing one means a tampered/foreign ledger
    or a claim older than the trimmed tail, and a prefix check would
    leave the gap ``(entry, rows]`` unverified yet resumed-into.  Any
    malformed entry makes the whole ledger unusable (fail closed)."""
    best = None
    for e in doc.get("windows") or []:
        try:
            r, nb, crc = int(e[0]), int(e[1]), str(e[2])
        except (TypeError, ValueError, IndexError):
            return None  # a torn ledger is an unusable ledger
        if r == rows:
            best = [r, nb, crc]
    return best


def verify_claim(product: str, rows: int, *, fmt: str,
                 row_bytes: int = 0, timeline=None,
                 strict: bool = True) -> Optional[bool]:
    """Content-verify a resume claim of ``rows`` rows/windows against the
    product's manifest ledger.

    Returns ``None`` when no manifest exists (legacy product — the
    caller keeps its length-only probe), ``True`` when the best covering
    claim's digest matches the bytes on disk, ``False`` on ANY doubt: a
    manifest that does not parse, a format/shape mismatch, a missing
    covering entry for a nonzero claim, or a digest mismatch (torn write
    inside the claimed region, tampered sidecar, replaced product) —
    fail closed, the caller restarts fresh.

    ``strict=False`` (the fsck walk) additionally returns ``None`` when
    the recompute ERRORED rather than mismatched — a file that cannot
    be read right now is usually a LIVE writer holding it (HDF5 write
    locks), and an observer must not quarantine work in progress; the
    resume paths keep ``strict=True`` because the resuming writer owns
    the file and an unreadable target must fail closed."""
    doc, problem = try_load_manifest(product)
    if doc is None:
        if problem is None:
            return None
        incr("integrity.manifest.mismatch")
        log.warning("%s: %s; refusing to trust the resume claim",
                    product, problem)
        return False
    try:
        doc_row_bytes = int(doc.get("row_bytes") or 0)
    except (TypeError, ValueError):
        doc_row_bytes = -1  # malformed: never matches
    if doc.get("format") != fmt or (
            row_bytes and doc_row_bytes not in (0, row_bytes)):
        incr("integrity.manifest.mismatch")
        log.warning("%s: manifest describes a different product shape "
                    "(format=%s row_bytes=%s); refusing the resume claim",
                    product, doc.get("format"), doc.get("row_bytes"))
        return False
    if rows <= 0:
        return True
    entry = _ledger_entry(doc, rows)
    if entry is None:
        incr("integrity.manifest.mismatch")
        log.warning("%s: manifest has no claim entry for row %d "
                    "(tampered/foreign ledger, or a claim older than "
                    "the trimmed tail); refusing the resume claim",
                    product, rows)
        return False
    e_rows, e_bytes, e_crc = entry
    expected = parse_crc(e_crc)
    if expected is None:
        incr("integrity.manifest.mismatch")
        return False
    t0 = time.perf_counter()
    err = False
    try:
        if fmt == "fbh5":
            got = _fbh5_rows_crc(product, e_rows)
        else:  # fil / hits: file-byte prefix space
            if os.path.getsize(product) < e_bytes:
                got = None
            else:
                got = crc32_file(product, 0, e_bytes)
    except Exception:  # noqa: BLE001 — classified below
        got = None
        err = True
    observe_verify(time.perf_counter() - t0, timeline)
    if err and not strict:
        log.warning("%s: claim unverifiable right now (read error — "
                    "a live writer?); leaving it alone", product)
        return None
    if got != expected:
        incr("integrity.resume.mismatch")
        log.warning(
            "%s: claimed region digest mismatch at row %d (%s != %s) — "
            "torn write or tampered sidecar; failing closed",
            product, e_rows, hex_crc(got) if got is not None else "<err>",
            e_crc)
        return False
    incr("integrity.resume.verified")
    return True


def _fbh5_rows_crc(path: str, rows: int) -> Optional[int]:
    """CRC over the logical dataset rows ``[0, rows)`` of an FBH5
    product, read in bounded row chunks (manual bitshuffle decode
    included via :func:`blit.io.fbh5.read_fbh5_data`)."""
    import h5py

    from blit.io.fbh5 import read_fbh5_data

    with h5py.File(path, "r") as h5:
        ds = h5["data"]
        if ds.shape[0] < rows:
            return None
        row_bytes = int(np.prod(ds.shape[1:])) * ds.dtype.itemsize
    step = max(1, _CRC_CHUNK // max(1, row_bytes))
    crc = 0
    for a in range(0, rows, step):
        b = min(rows, a + step)
        slab = read_fbh5_data(path, (slice(a, b), slice(None), slice(None)))
        crc = crc32_update(crc, np.ascontiguousarray(slab))
    return crc


def verify_product(path: str, *, timeline=None
                   ) -> Tuple[Optional[Dict], List[str]]:
    """Verify one product against its manifest → ``(manifest, problems)``.

    No manifest → ``(None, [])`` (unmanifested — reported, not failed).
    Complete manifests verify size + whole-file CRC (any single flipped
    byte anywhere in the file is caught); incomplete manifests (a
    resumable writer mid-stream or crashed) verify the newest claimed
    prefix through the ledger.  Every problem string is operator-facing.
    """
    doc, problem = try_load_manifest(path)
    if doc is None:
        return (None, [problem] if problem else [])
    problems: List[str] = []
    if not os.path.exists(path):
        problems.append("product missing (manifest orphaned)")
        return doc, problems
    size = os.path.getsize(path)
    try:
        want = doc.get("bytes")
        want = int(want) if want is not None else None
        claimed_rows = int(doc.get("rows") or 0)
    except (TypeError, ValueError):
        # Malformed numeric fields: the manifest cannot be trusted and
        # the product cannot be verified — the failure mode (fail
        # closed), not an exception out of the fsck walk.
        return doc, ["malformed manifest fields (tampered/torn?)"]
    if doc.get("complete"):
        want_crc = parse_crc(doc.get("crc32"))
        if want is not None and size != want:
            problems.append(
                f"size {size} != manifest {want} (product replaced or "
                "truncated after publish)")
        elif want_crc is None:
            problems.append("manifest carries no whole-file digest")
        else:
            t0 = time.perf_counter()
            got = crc32_file(path)
            observe_verify(time.perf_counter() - t0, timeline)
            if got != want_crc:
                problems.append(
                    f"content digest mismatch ({hex_crc(got)} != "
                    f"{doc['crc32']})")
    else:
        # strict=False: an in-progress product a live writer holds
        # (HDF5 write locks make it unreadable from outside) verifies
        # as None and is left alone — fsck counts it in_progress.
        ok = verify_claim(path, claimed_rows,
                          fmt=str(doc.get("format")),
                          timeline=timeline, strict=False)
        if ok is False:
            problems.append("claimed-prefix digest mismatch "
                            "(torn write or tampered sidecar)")
    if problems:
        incr("integrity.manifest.mismatch")
    return doc, problems


# -- quarantine + health -----------------------------------------------------

_WATCH_LOCK = threading.Lock()
_WATCHED_QUARANTINES: set = set()


def quarantine_health() -> Optional[Dict]:
    """The ``/healthz`` contributor (ISSUE 13 satellite): degraded while
    any watched ``.quarantine/`` holds entries — corruption was detected
    and an operator has not yet triaged it."""
    entries = 0
    dirs: List[str] = []
    with _WATCH_LOCK:
        watched = list(_WATCHED_QUARANTINES)
    for d in watched:
        try:
            names = [n for n in os.listdir(d) if not n.startswith(".")]
        except OSError:
            continue
        if names:
            entries += len(names)
            dirs.append(d)
    if entries:
        return {"degraded": True,
                "reason": f"quarantine-nonempty:{entries}",
                "entries": entries, "dirs": sorted(dirs)}
    return {}


def watch_quarantine(qdir: str) -> None:
    """Register a quarantine dir with the health surface (idempotent);
    installs the ``integrity`` health hook on the monitor plane."""
    with _WATCH_LOCK:
        _WATCHED_QUARANTINES.add(os.path.abspath(qdir))
    try:
        from blit import monitor

        monitor.register_health_hook("integrity", quarantine_health)
    except Exception:  # noqa: BLE001 — health wiring must not fail callers
        pass


def quarantine_move(paths: List[str], into_dir: str) -> List[str]:
    """Move ``paths`` (those that exist) into ``into_dir``'s
    ``.quarantine/``, suffixing on collision.  Returns the destinations.
    The move is the containment action: a corrupt artifact must stop
    being servable/resumable NOW, while staying inspectable."""
    qdir = os.path.join(into_dir, QUARANTINE_DIR)
    os.makedirs(qdir, exist_ok=True)
    watch_quarantine(qdir)
    moved = []
    for p in paths:
        if not os.path.exists(p):
            continue
        dest = os.path.join(qdir, os.path.basename(p))
        n = 0
        while os.path.exists(dest):
            n += 1
            dest = os.path.join(qdir, f"{os.path.basename(p)}.{n}")
        shutil.move(p, dest)
        moved.append(dest)
    if moved:
        incr("integrity.quarantine", len(moved))
    return moved


# -- fsck --------------------------------------------------------------------


def _cache_meta(dirpath: str, fn: str, names) -> Optional[Dict]:
    """Parse ``fn`` as a serve-cache meta sidecar (``<fp>.json`` with a
    ``fingerprint`` and a ``<fp>.h5`` sibling); None when it is not one.
    A meta that LOOKS like one but does not parse returns
    ``{"_torn": True}`` — fail closed."""
    if (not fn.endswith(".json") or fn.endswith(MANIFEST_SUFFIX)
            or fn.endswith(DIGESTS_SUFFIX)):
        return None
    data_sibling = fn[:-5] + ".h5"
    try:
        with open(os.path.join(dirpath, fn)) as f:
            doc = json.load(f)
        if not isinstance(doc, dict) or "fingerprint" not in doc:
            return None
        return doc
    except (OSError, ValueError):
        return {"_torn": True} if data_sibling in names else None


def fsck(root: str, *, repair: bool = False, quarantine: bool = True,
         timeline=None) -> Dict:
    """Walk ``root`` verifying every manifested product and every
    serve-cache entry; quarantine what fails.  Returns the report dict
    (the ``blit fsck`` body; ``bad`` empty == clean tree).

    ``repair=True`` additionally re-derives quarantined CACHE entries
    whose meta carries a recipe: the fingerprint is a content-addressed
    recipe over (raw identity, reducer config), so the entry rebuilds
    through the same reduce path the serve layer would take on a miss —
    and only re-publishes when the recomputed fingerprint still matches
    (an input that changed since is reported, not guessed at)."""
    root = os.path.abspath(root)
    report: Dict = {
        "root": root, "checked": 0, "ok": 0, "unmanifested": 0,
        "in_progress": 0, "bad": [], "quarantined": [],
        "repaired": [], "repair_failed": [],
    }

    def _bad(dirpath: str, path: str, kind: str, problems: List[str],
             extra_paths: List[str]) -> None:
        entry = {"path": os.path.relpath(path, root), "kind": kind,
                 "problems": problems}
        if quarantine:
            moved = quarantine_move([path] + extra_paths, dirpath)
            entry["quarantined"] = [os.path.relpath(m, root)
                                    for m in moved]
            report["quarantined"].extend(entry["quarantined"])
        report["bad"].append(entry)

    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = sorted(d for d in dirnames if d != QUARANTINE_DIR)
        names = set(filenames)
        for fn in sorted(filenames):
            full = os.path.join(dirpath, fn)
            if fn.endswith(DIGESTS_SUFFIX):
                member = os.path.join(dirpath, fn[:-len(DIGESTS_SUFFIX)])
                report["checked"] += 1
                if not os.path.exists(member):
                    problems = ["RAW member missing (sidecar orphaned)"]
                else:
                    t0 = time.perf_counter()
                    problems = verify_raw_member(member)
                    observe_verify(time.perf_counter() - t0, timeline)
                if problems:
                    # Report-only: RAW members are the source of truth;
                    # ingest masks their bad blocks, operators decide
                    # whether to re-fetch from the recorder.
                    report["bad"].append(
                        {"path": os.path.relpath(member, root),
                         "kind": "raw", "problems": problems,
                         "quarantined": []})
                else:
                    report["ok"] += 1
                continue
            if fn.endswith(MANIFEST_SUFFIX):
                product = os.path.join(dirpath, fn[:-len(MANIFEST_SUFFIX)])
                report["checked"] += 1
                doc, problems = verify_product(product, timeline=timeline)
                if doc is None and problems:
                    # Torn manifest: quarantine it WITH its product —
                    # a product under an untrustworthy manifest is
                    # unverifiable, which is the failure mode.
                    _bad(dirpath, product, "product", problems, [full])
                    continue
                if doc is not None and not doc.get("complete"):
                    report["in_progress"] += 1
                if problems:
                    _bad(dirpath, product, "product", problems,
                         [full, product + ".cursor",
                          product + ".stream-cursor"])
                else:
                    report["ok"] += 1
                continue
            meta = _cache_meta(dirpath, fn, names)
            if meta is not None:
                fp = fn[:-5]
                data = os.path.join(dirpath, fp + ".h5")
                report["checked"] += 1
                problems = []
                if meta.get("_torn"):
                    problems.append("unreadable/torn cache meta")
                elif not os.path.exists(data):
                    problems.append("cache data file missing")
                else:
                    want = parse_crc(meta.get("crc32"))
                    if want is None:
                        # Pre-integrity entry: structural probe only.
                        from blit.io.fbh5 import resume_target_ok

                        if not resume_target_ok(
                                data, int(meta.get("nifs", -1)),
                                int(meta.get("nchans", -1)),
                                int(meta.get("nsamps", -1))):
                            problems.append(
                                "entry unreadable (no content digest "
                                "recorded; structural probe failed)")
                    else:
                        t0 = time.perf_counter()
                        got = crc32_file(data)
                        observe_verify(time.perf_counter() - t0, timeline)
                        if got != want:
                            problems.append(
                                f"cache entry content digest mismatch "
                                f"({hex_crc(got)} != {meta['crc32']})")
                if problems:
                    incr("integrity.cache.corrupt")
                    _bad(dirpath, data, "cache", problems, [full])
                else:
                    report["ok"] += 1
                continue
            if fn.endswith(_PRODUCT_EXTS):
                if fn + MANIFEST_SUFFIX in names:
                    continue  # verified via its manifest above
                if fn.endswith(".h5") and fn[:-3] + ".json" in names:
                    continue  # a cache data file, verified via its meta
                report["unmanifested"] += 1
    if repair:
        _repair_quarantined(root, report)
    report["clean"] = not report["bad"]
    return report


def _strip_collision(name: str) -> str:
    """Undo the quarantine collision suffix (``x.fil.2`` → ``x.fil``)."""
    stem, _, tail = name.rpartition(".")
    return stem if stem and tail.isdigit() else name


def _repair_quarantined(root: str, report: Dict) -> None:
    """The ``fsck --repair`` pass: rebuild quarantined cache entries from
    their recorded recipes (ISSUE 13 tentpole 3), and retire any other
    quarantined artifact whose original path now holds a VERIFIED
    replacement (the operator re-reduced the product; the corpse is
    superseded).  Anything that cannot be repaired stays quarantined —
    and keeps ``/healthz`` degraded — for a human."""
    for dirpath, dirnames, _files in os.walk(root):
        if QUARANTINE_DIR not in dirnames:
            continue
        qdir = os.path.join(dirpath, QUARANTINE_DIR)
        try:
            qnames = sorted(os.listdir(qdir))
        except OSError:
            continue
        handled: set = set()
        for fn in qnames:
            if fn in handled or fn.endswith(MANIFEST_SUFFIX):
                continue
            if not fn.endswith(".json"):
                continue
            qmeta = os.path.join(qdir, fn)
            try:
                with open(qmeta) as f:
                    meta = json.load(f)
            except (OSError, ValueError):
                continue
            if not isinstance(meta, dict) or "fingerprint" not in meta:
                continue
            fp = meta.get("fingerprint")
            recipe = meta.get("recipe")
            rel = os.path.relpath(qmeta, root)
            if not isinstance(recipe, dict):
                report["repair_failed"].append(
                    {"path": rel, "why": "no recipe recorded"})
                continue
            try:
                got_fp = _rederive_cache_entry(dirpath, fp, recipe)
            except Exception as e:  # noqa: BLE001 — reported, not raised
                report["repair_failed"].append(
                    {"path": rel, "why": f"{type(e).__name__}: {e}"})
                continue
            if got_fp != fp:
                report["repair_failed"].append(
                    {"path": rel,
                     "why": "raw input changed since the entry was "
                            "published (fingerprint differs) — the old "
                            "bytes are unrecoverable"})
                continue
            # The rebuilt entry is live again; the corpse can go.
            for stale in (fn, fn[:-5] + ".h5"):
                handled.add(stale)
                try:
                    os.unlink(os.path.join(qdir, stale))
                except OSError:
                    pass
            report["repaired"].append(
                {"fingerprint": fp, "cache_dir": os.path.relpath(
                    dirpath, root) or "."})
            incr("integrity.repair")
        # Superseded-corpse retirement: a quarantined product (and its
        # sidecars) whose original path now verifies clean again.
        for fn in sorted(set(os.listdir(qdir)) - handled
                         if os.path.isdir(qdir) else ()):
            orig_name = _strip_collision(fn)
            base = orig_name
            for suffix in (MANIFEST_SUFFIX, ".cursor", ".stream-cursor"):
                if base.endswith(suffix):
                    base = base[:-len(suffix)]
                    break
            original = os.path.join(dirpath, base)
            if not os.path.exists(original):
                continue
            doc, problems = verify_product(original)
            if doc is None or problems:
                # Only a replacement that POSITIVELY verified (manifest
                # present, digests clean) supersedes a corpse — an
                # unmanifested file at the path proves nothing, and the
                # corpse is the only forensic copy.
                continue
            try:
                os.unlink(os.path.join(qdir, fn))
            except OSError:
                continue
            report["repaired"].append(
                {"path": os.path.relpath(os.path.join(qdir, fn), root),
                 "superseded_by": os.path.relpath(original, root)})
            incr("integrity.repair")
        try:
            if os.path.isdir(qdir) and not os.listdir(qdir):
                os.rmdir(qdir)
        except OSError:
            pass


def _rederive_cache_entry(cache_dir: str, fp: str, recipe: Dict) -> str:
    """Re-run the reduction a cache entry's recipe describes and
    re-publish it — the serve layer's miss path, driven by fsck.
    Returns the recomputed fingerprint (callers compare)."""
    from blit.serve.cache import ProductCache, fingerprint_for
    from blit.serve.service import ProductRequest

    req = ProductRequest.from_recipe(recipe)
    reducer = req.reducer()
    got_fp = fingerprint_for(reducer, req.raw_source)
    if got_fp != fp:
        return got_fp
    header, data = reducer.reduce(req.raw_source)
    cache = ProductCache(cache_dir, ram_bytes=0)
    cache.put(fp, header, data, recipe=recipe)
    # put() downgrades a failed disk publish to RAM-only (serve-path
    # semantics) — here the DISK entry is the whole point, and the
    # caller is about to delete the only forensic copy: require the
    # re-published entry to actually verify before reporting success.
    if cache.verify_entry(fp) is not True:
        raise RuntimeError(
            "re-derived entry failed to publish/verify on disk; "
            "keeping the quarantined copy")
    return got_fp


# -- the background scrubber -------------------------------------------------


class Scrubber:
    """Budget-bounded background verification of a disk cache tier
    (ISSUE 13 tentpole 3): one entry per tick, round-robin over the
    index, with an inter-tick pause sized so verified bytes/s stays
    under ``bytes_per_s`` — scrubbing samples the archive *between*
    requests instead of competing with them.

    Opt-in via ``BLIT_SCRUB_INTERVAL`` / SiteConfig
    (:func:`blit.config.scrub_defaults`); :class:`blit.serve.service
    .ProductService` starts one automatically when enabled.  Counters
    (``integrity.scrub.ok`` / ``integrity.scrub.corrupt``) and the
    ``integrity.verify_s`` histogram land on the timeline, so the PR 10
    monitor plane (``/metrics``, ``blit top``, the spool) shows scrub
    progress live; a corrupt entry is quarantined through the cache
    (``evict.corrupt`` + ``.quarantine/`` + the degraded ``/healthz``).
    ``tick()``/``scrub_once()`` are synchronous for tests and drills.
    """

    def __init__(self, cache, *, interval_s: float = 30.0,
                 bytes_per_s: Optional[float] = None, timeline=None,
                 quarantine: bool = True):
        from blit.observability import Timeline

        self.cache = cache
        self.interval_s = max(0.01, float(interval_s))
        self.bytes_per_s = bytes_per_s
        self.timeline = timeline if timeline is not None else Timeline()
        self.quarantine = quarantine
        self._cursor = 0
        self._debt_s = 0.0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.scrubbed = 0
        self.corrupt = 0

    def scrub_once(self) -> Optional[Dict]:
        """Verify the next disk-tier entry (None when the tier is
        empty, or when the sampled entry vanished mid-tick — a routine
        LRU-eviction race, NOT corruption).  Returns
        ``{"fp", "ok", "bytes", "seconds"}``."""
        fps = sorted(self.cache.index())
        if not fps:
            return None
        fp = fps[self._cursor % len(fps)]
        self._cursor += 1
        try:
            nbytes = os.path.getsize(self.cache.data_path(fp))
        except OSError:
            nbytes = 0
        t0 = time.perf_counter()
        ok = self.cache.verify_entry(fp, quarantine=self.quarantine)
        dt = time.perf_counter() - t0
        if ok is None:
            return None  # evicted between index() and the verify
        self.scrubbed += 1
        if ok:
            self.timeline.count("integrity.scrub.ok")
        else:
            self.corrupt += 1
            self.timeline.count("integrity.scrub.corrupt")
            incr("integrity.scrub.corrupt")
        observe_verify(dt, self.timeline)
        if self.bytes_per_s:
            # Debt-based pacing: a big entry buys a longer pause.
            self._debt_s = max(0.0, nbytes / self.bytes_per_s - dt)
        return {"fp": fp, "ok": bool(ok), "bytes": nbytes,
                "seconds": dt}

    def _loop(self) -> None:
        while not self._stop.wait(self.interval_s + self._debt_s):
            self._debt_s = 0.0
            try:
                self.scrub_once()
            except Exception:  # noqa: BLE001 — scrubbing must not die
                log.warning("scrub tick failed", exc_info=True)

    def start(self) -> "Scrubber":
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._loop, name="blit-scrubber", daemon=True)
            self._thread.start()
        return self

    def close(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.close()
