"""Ingest autotuner: close the kernel↔end-to-end gap per rig (ISSUE 8).

Kernels move 13–145 GB/s/chip while ``rig_ingest_gbps`` sat at
0.011–0.018 across every bench round — the end-to-end rate is set by how
well the ingest knobs hide the slow legs (host read, H2D, D2H readback,
disk write) behind compute, and the right knob values are a property of
the RIG (link bandwidths, core count, page-cache behavior), not of the
code.  This module makes those knobs measured-per-rig instead of
guessed-per-checkout:

- :func:`tune` — deterministic coordinate descent over the ingest knob
  space (``chunk_frames`` / ``prefetch_depth`` / ``out_depth``) against
  any measure function.  Offline, ``blit tune`` drives it with real
  timed reductions; in tests a simulated stage-cost model replaces the
  stopwatch, so convergence is deterministic on CPU.
- :class:`TuningProfile` — the persisted winner: a content-addressed
  per-rig profile keyed like reduction fingerprints
  (:func:`rig_fingerprint` = sha256 over the canonical JSON of the rig
  identity + the workload's knob surface).  ``scan``/``serve``/
  ``stream`` load it automatically: every
  :class:`blit.pipeline.RawReducer` whose ingest knobs were left unset
  consults :func:`lookup` at construction (``BLIT_TUNE=0`` disables).
  A stale profile — different host, backend, device kind, or workload
  shape — hashes to a different key and is simply never found; a
  tampered/corrupt profile file is ignored (its embedded key no longer
  matches its content).
- :class:`OnlineTuner` — convergence during the first windows of a live
  reduction: after a warmup of observed chunks it derives a
  recommendation from the per-stage timeline (the same cost heuristics
  the offline sweep discovers empirically), publishes it as
  ``tune.rec_*`` gauges, and persists it as a profile when
  ``BLIT_TUNE_ONLINE=1`` — so a fleet converges rig-by-rig without an
  operator ever running the CLI.

Profiles live under ``BLIT_TUNE_DIR`` (else ``SiteConfig.tune_dir``,
else ``~/.cache/blit/tune``), one JSON file per fingerprint, written
atomically (tmp + rename) like every other blit sidecar.
"""

from __future__ import annotations

import hashlib
import json
import logging
import os
import time
from dataclasses import asdict, dataclass, field
from typing import Callable, Dict, List, Optional, Tuple

log = logging.getLogger("blit.tune")

KNOBS = ("chunk_frames", "prefetch_depth", "out_depth")

# Knob bounds: depths below 2 serialize the planes; depths above 8 pin
# more chunk buffers than they hide latency for (each held chunk is host
# RSS and — on the output side — device HBM).
MIN_DEPTH, MAX_DEPTH = 2, 8
MAX_CHUNK_FRAMES = 1 << 12

PROFILE_VERSION = 1


# -- rig fingerprint ------------------------------------------------------

def _rig_identity() -> Dict:
    """Host + accelerator identity — what makes a tuning result
    non-portable.  Probed lazily and defensively: a rig whose jax is
    broken still gets a (host-keyed) fingerprint."""
    from blit import observability

    ident = {"host": observability.hostname()}
    try:
        import jax

        ident["backend"] = jax.default_backend()
        devs = jax.devices()
        ident["device_kind"] = devs[0].device_kind if devs else "none"
        ident["device_count"] = len(devs)
    except Exception:  # noqa: BLE001 — fingerprint must never raise
        ident["backend"] = "unknown"
        ident["device_kind"] = "unknown"
        ident["device_count"] = 0
    return ident


def rig_fingerprint(*, nfft: int, nint: int, ntap: int = 4,
                    stokes: str = "I", window: str = "hamming",
                    fqav_by: int = 1, dtype: str = "float32",
                    fft_method: str = "auto", nbits: int = 32,
                    workload: str = "reduce") -> Tuple[str, Dict]:
    """``(key, identity)`` of one (rig, workload-shape) pair — the
    content address a tuning profile is stored and looked up under,
    built exactly like :func:`blit.serve.cache.reduction_fingerprint`
    (canonical JSON → sha256) but over the rig identity + the knob
    surface that shapes per-chunk cost, NOT over any particular
    recording (tuning transfers across same-shaped inputs)."""
    ident = _rig_identity()
    ident.update(
        workload=workload, nfft=int(nfft), ntap=int(ntap), nint=int(nint),
        stokes=stokes, window=window, fqav_by=int(fqav_by), dtype=dtype,
        fft_method=fft_method, nbits=int(nbits),
    )
    blob = json.dumps(ident, sort_keys=True).encode()
    return hashlib.sha256(blob).hexdigest(), ident


# -- profile store --------------------------------------------------------

@dataclass
class TuningProfile:
    """One rig's converged ingest knobs, with provenance: the fingerprint
    identity it was measured under, the score that won, and the stage
    quantiles behind it (so a bench report can embed *why* these knobs,
    not just *which*)."""

    key: str
    rig: Dict
    chunk_frames: int
    prefetch_depth: int
    out_depth: int
    score_gbps: float = 0.0
    trials: int = 0
    stages: Dict = field(default_factory=dict)
    source: str = "offline"  # "offline" (blit tune) | "online"
    created_s: float = 0.0
    version: int = PROFILE_VERSION
    # Channel count of the recording the profile was MEASURED on.  NOT
    # part of the fingerprint key (lookup happens before any recording
    # is open, and tuning transfers across same-shaped workloads) — but
    # per-chunk staging and stage cost scale linearly with it, so the
    # reducer warns when a loaded profile was measured on a
    # different-width recording.  0 = unknown (legacy profile).
    tuned_nchan: int = 0

    def knobs(self) -> Dict[str, int]:
        return {
            "chunk_frames": int(self.chunk_frames),
            "prefetch_depth": int(self.prefetch_depth),
            "out_depth": int(self.out_depth),
        }

    def provenance(self) -> Dict:
        """The compact provenance block bench/ingest-bench embed."""
        return {
            "key": self.key,
            "source": self.source,
            "score_gbps": self.score_gbps,
            "trials": self.trials,
            "created_s": self.created_s,
            "tuned_nchan": self.tuned_nchan,
            **self.knobs(),
        }


def enabled() -> bool:
    """Auto-load kill switch: ``BLIT_TUNE=0`` makes every reducer fall
    back to the built-in defaults (drills, A/B runs, tests)."""
    return os.environ.get("BLIT_TUNE", "1") != "0"


def profile_dir(config=None) -> str:
    env = os.environ.get("BLIT_TUNE_DIR")
    if env:
        return env
    if config is None:
        # Site default: every production caller (reducer lookup, online
        # persist, CLI) passes config=None, so SiteConfig.tune_dir must
        # apply here — the hostmem staging_pool_bytes rule.
        from blit.config import DEFAULT as config
    cfg_dir = getattr(config, "tune_dir", None)
    if cfg_dir:
        return cfg_dir
    return os.path.join(os.path.expanduser("~"), ".cache", "blit", "tune")


def _profile_path(key: str, config=None) -> str:
    return os.path.join(profile_dir(config), f"tune-{key[:24]}.json")


def save_profile(profile: TuningProfile, config=None) -> str:
    """Persist atomically; returns the path.  The file embeds the full
    fingerprint identity so :func:`load_profile` can verify the content
    still hashes to the key it is stored under."""
    path = _profile_path(profile.key, config)
    os.makedirs(os.path.dirname(path), exist_ok=True)
    if not profile.created_s:
        profile.created_s = time.time()
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(asdict(profile), f, sort_keys=True)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    return path


def load_profile(key: str, config=None) -> Optional[TuningProfile]:
    """The profile stored under ``key`` — or None when absent, corrupt,
    from a different profile version, or STALE (its embedded identity no
    longer hashes to ``key``: a copied-over profile from another rig, a
    hand-edited file, or a changed fingerprint recipe all land here and
    are ignored rather than trusted)."""
    path = _profile_path(key, config)
    try:
        with open(path) as f:
            doc = json.load(f)
        prof = TuningProfile(**doc)
    except (OSError, ValueError, TypeError):
        return None
    if prof.version != PROFILE_VERSION or prof.key != key:
        return None
    blob = json.dumps(prof.rig, sort_keys=True).encode()
    if hashlib.sha256(blob).hexdigest() != key:
        log.warning("tuning profile %s is stale (identity mismatch); "
                    "ignoring", path)
        return None
    # Knob sanity is part of "corrupt is ignored, not trusted": the
    # integrity hash covers only the rig identity, so non-numeric or
    # out-of-range knob values (hand edits, partial writes) must land
    # here — bounded to the sweep's own ladder limits, never a crash.
    try:
        knobs = prof.knobs()
        prof.tuned_nchan = int(prof.tuned_nchan or 0)
    except (TypeError, ValueError):
        log.warning("tuning profile %s has non-numeric knobs; ignoring",
                    path)
        return None
    if not (0 < knobs["chunk_frames"] <= MAX_CHUNK_FRAMES
            and MIN_DEPTH <= knobs["prefetch_depth"] <= MAX_DEPTH
            and MIN_DEPTH <= knobs["out_depth"] <= MAX_DEPTH):
        log.warning("tuning profile %s has out-of-range knobs %s; "
                    "ignoring", path, knobs)
        return None
    return prof


def lookup(config=None, **fingerprint_kw) -> Optional[TuningProfile]:
    """The active profile for this rig + workload shape, or None.  Cheap
    when no profile exists (one stat); disabled by ``BLIT_TUNE=0``."""
    if not enabled():
        return None
    key, _ = rig_fingerprint(**fingerprint_kw)
    return load_profile(key, config)


# -- offline sweep --------------------------------------------------------

def _cf_bound(nint: int, max_chunk_frames: Optional[int] = None) -> int:
    """The effective chunk_frames ceiling: the caller's recording bound
    capped by the global ladder limit, floored to an nint multiple
    (chunk_frames must fold evenly) but never below nint itself."""
    b = min(MAX_CHUNK_FRAMES,
            max_chunk_frames if max_chunk_frames else MAX_CHUNK_FRAMES)
    return max(nint, (b // nint) * nint)


def normalize_base(base: Optional[Dict[str, int]] = None, *, nint: int = 1,
                   max_chunk_frames: Optional[int] = None) -> Dict[str, int]:
    """The exact starting knob set :func:`tune` will measure first —
    defaults filled, chunk_frames rounded UP to an nint multiple the way
    ``RawReducer.__post_init__`` executes it (so the sweep measures the
    knob value that actually runs), then clamped into the same bounds
    ``load_profile`` enforces plus the caller's recording bound.
    Callers that warm up untimed at the base (``blit tune``) must warm
    at THIS value, or a clamped base pays its jit compile inside the
    first timed trial and the reported baseline understates."""
    bound = _cf_bound(nint, max_chunk_frames)
    cur = {"chunk_frames": max(nint, 8), "prefetch_depth": 2, "out_depth": 2}
    if base:
        cur.update({k: int(v) for k, v in base.items() if k in KNOBS})
    cf = -(-max(nint, cur["chunk_frames"]) // nint) * nint
    cur["chunk_frames"] = min(bound, cf)
    for k in ("prefetch_depth", "out_depth"):
        cur[k] = max(MIN_DEPTH, min(MAX_DEPTH, cur[k]))
    return cur


def _ladder(knob: str, value: int, nint: int,
            max_chunk_frames: int = MAX_CHUNK_FRAMES) -> List[int]:
    """Deterministic candidate neighborhood around ``value`` (the
    coordinate-descent move set).  chunk_frames moves multiplicatively —
    its effect (dispatch amortization vs HBM/latency) is log-scaled —
    while the depths move by single steps inside [MIN_DEPTH, MAX_DEPTH]."""
    if knob == "chunk_frames":
        vals = {max(nint, (value // 2 // nint) * nint), value,
                min(max_chunk_frames, value * 2)}
        return sorted(v for v in vals if nint <= v <= max_chunk_frames)
    vals = {max(MIN_DEPTH, value - 1), value, min(MAX_DEPTH, value + 1)}
    return sorted(vals)


def tune(measure: Callable[[Dict[str, int]], float], *,
         base: Optional[Dict[str, int]] = None, nint: int = 1,
         max_trials: int = 24, max_passes: int = 6,
         rel_tol: float = 0.01,
         max_chunk_frames: Optional[int] = None,
         ) -> Tuple[Dict[str, int], List[Dict]]:
    """Coordinate descent over the ingest knobs against ``measure``
    (knobs → score, higher is better; GB/s in production, a simulated
    cost model in tests).

    Deterministic: candidates are a fixed ladder around the current
    value, evaluations are memoized (a knob setting is measured at most
    once), a move must win by ``rel_tol`` relative margin (ties keep the
    SMALLER knob value — cheaper in host/device memory), and passes
    repeat until a full pass moves nothing or ``max_trials``
    measurements were spent.  Returns ``(best_knobs, trials)`` with
    ``trials`` the evaluation log in measurement order.

    ``max_chunk_frames`` bounds the chunk_frames ladder below the global
    MAX_CHUNK_FRAMES — callers measuring against a finite recording pass
    total_frames//2 so every candidate still fills ≥2 full chunks
    (a chunk spanning most of the file scores a degenerate
    near-zero-overhead measurement that would otherwise always win).
    """
    cf_bound = _cf_bound(nint, max_chunk_frames)
    # Normalize into the same bounds load_profile enforces (plus the
    # caller's recording bound) — otherwise a base above the cap can
    # WIN, persist, and be silently rejected by every later lookup.
    cur = normalize_base(base, nint=nint, max_chunk_frames=max_chunk_frames)
    memo: Dict[Tuple, float] = {}
    trials: List[Dict] = []

    def score(knobs: Dict[str, int]) -> Optional[float]:
        key = tuple(knobs[k] for k in KNOBS)
        if key in memo:
            return memo[key]
        if len(memo) >= max_trials:
            return None
        s = float(measure(dict(knobs)))
        memo[key] = s
        trials.append({**knobs, "score": s})
        return s

    best = score(cur)
    if best is None:
        raise ValueError("max_trials=0 leaves nothing to tune")
    for _ in range(max_passes):
        moved = False
        for knob in KNOBS:
            for cand in _ladder(knob, cur[knob], nint,
                                max_chunk_frames=cf_bound):
                if cand == cur[knob]:
                    continue
                trial = dict(cur, **{knob: cand})
                s = score(trial)
                if s is None:
                    return cur, trials  # budget spent
                if s > best * (1.0 + rel_tol):
                    # Strictly better by the margin.
                    cur, best = trial, s
                    moved = True
                elif s >= best * (1.0 - rel_tol) and cand < cur[knob]:
                    # A tie within the margin prefers the SMALLER knob
                    # (cheaper in host/device memory).  ``best`` keeps
                    # the highest score seen at the current point so
                    # repeated tie-moves cannot ratchet the bar down by
                    # rel_tol per pass; tie-moves alone also do not
                    # extend the pass loop (``moved`` stays False), so a
                    # flat surface terminates.
                    cur = trial
                    best = max(best, s)
        if not moved:
            break
    return cur, trials


# -- online convergence ---------------------------------------------------

@dataclass
class Recommendation:
    knobs: Dict[str, int]
    reasons: List[str]


def recommend_from_stages(stages: Dict[str, Dict], hists: Dict[str, Dict],
                          current: Dict[str, int], *,
                          nint: int = 1) -> Recommendation:
    """Derive the next knob set from observed per-stage costs — the pure
    decision core behind :class:`OnlineTuner` (tested against a
    simulated cost model; no TPU needed).

    Every heuristic reads only what is POPULATED mid-stream: the
    ``dispatch`` stage (consumer-side enqueue, per chunk), the
    ``device`` stage (the readback thread's lag-synchronized waits —
    blit/outplane.py records it per chunk), the ``ingest`` stage (the
    producer's file reads) and the ``out.*`` histograms.  The ``stream``
    wall stage is deliberately NOT used — its context is still open
    when the online tuner fires, so its seconds read zero until the
    stream ends.

    Heuristics, in the order a saturating ingest plane develops them:

    - **Dispatch-bound** (per-chunk fixed overhead — the consumer-side
      ``dispatch`` stage plus the producer's chunk framing — is a big
      fraction of per-chunk device work): double ``chunk_frames`` to
      amortize it.
    - **Readback-lagged** (``out.readback_lag_s`` median well above the
      per-chunk service latency median: dispatches PERSISTENTLY queue
      faster than the readback thread drains — medians, because over a
      handful of warmup samples p99 is just the max and one compile-
      sized outlier would fire it on every cold run): deepen
      ``out_depth``.
    - **Producer-bound** (per-chunk file-read seconds exceed the
      per-chunk hidden work — device wait + dispatch — so the consumer
      regularly waits on the producer): deepen ``prefetch_depth`` so
      more read-ahead runs before it is needed.
    """

    def sec(name: str) -> float:
        return float(stages.get(name, {}).get("seconds",
                                              stages.get(name, {}).get("s", 0.0)))

    def calls(name: str) -> int:
        return int(stages.get(name, {}).get("calls", 0))

    rec = dict(current)
    reasons: List[str] = []
    # Chunks observed so far: the dispatch stage ticks once per chunk on
    # the async path; fall back to device calls for sync-shaped tables.
    nchunks = max(1, calls("dispatch") or calls("device"))
    per_disp = sec("dispatch") / nchunks
    per_dev = sec("device") / max(1, calls("device"))
    if per_dev > 0 and per_disp / per_dev > 0.25:
        rec["chunk_frames"] = min(
            MAX_CHUNK_FRAMES,
            max(nint, (current["chunk_frames"] * 2 // nint) * nint),
        )
        reasons.append(
            f"dispatch-bound: {per_disp:.2e}s fixed per chunk vs "
            f"{per_dev:.2e}s device — amortize with bigger chunks"
        )
    lag = hists.get("out.readback_lag_s", {})
    latency = hists.get("out.chunk_latency_s", {})
    # Median vs median, NOT p99: with only ~warmup samples p99 is the
    # max, and chunk 1's compile-sized lag sample (recorded by the
    # readback thread, racing the snapshot above) would trip it on
    # every cold run.  A rig that needs a deeper ring lags PERSISTENTLY
    # — the median shows it; one warmup outlier doesn't.
    if (lag.get("n", 0) and latency.get("n", 0)
            and lag.get("p50", 0.0) > 2.0 * max(latency.get("p50", 0.0),
                                                1e-9)):
        rec["out_depth"] = min(MAX_DEPTH, current["out_depth"] + 1)
        reasons.append(
            f"readback-lagged: lag p50 {lag['p50']:.2e}s vs service p50 "
            f"{latency.get('p50', 0.0):.2e}s — deepen the readback ring"
        )
    per_ing = sec("ingest") / nchunks
    per_hidden = per_dev + per_disp
    if per_ing > 0 and per_ing > per_hidden:
        rec["prefetch_depth"] = min(MAX_DEPTH, current["prefetch_depth"] + 1)
        reasons.append(
            f"producer-bound: {per_ing:.2e}s file read per chunk vs "
            f"{per_hidden:.2e}s hidden work — deepen read-ahead"
        )
    return Recommendation(knobs=rec, reasons=reasons)


class OnlineTuner:
    """Converge a recommendation during the first windows of a streaming
    reduction (class docstring in the module header).

    The reducer calls :meth:`observe_chunk` once per dispatched chunk;
    after ``warmup_chunks`` the tuner reads the timeline ONCE, derives
    the recommendation, publishes ``tune.rec_*`` gauges, and goes
    dormant (zero further per-chunk cost).  :meth:`maybe_persist` at
    stream end writes the recommendation as an ``online`` profile when
    ``BLIT_TUNE_ONLINE=1`` and the recommendation actually moved a knob.
    """

    def __init__(self, timeline, current: Dict[str, int], *, nint: int = 1,
                 warmup_chunks: int = 8):
        self._tl = timeline
        self._current = dict(current)
        self._nint = nint
        self.warmup_chunks = max(2, warmup_chunks)
        self._seen = 0
        self._snap = None
        self._hist_snap: Dict[str, Dict] = {}
        self.recommendation: Optional[Recommendation] = None

    @property
    def converged(self) -> bool:
        return self.recommendation is not None

    def observe_chunk(self) -> None:
        if self.recommendation is not None:
            return
        self._seen += 1
        if self._snap is None:
            # Chunk 1's dispatch stage carries the XLA compile.  Folding
            # it into per-chunk cost makes EVERY cold run look
            # dispatch-bound — and with BLIT_TUNE_ONLINE=1 the persisted
            # chunk_frames would ratchet x2 per run (each new shape
            # recompiles, re-tripping the heuristic).  Snapshot after
            # the first chunk and recommend from the post-warmup DELTA.
            self._snap = self._tl.snapshot()
            self._hist_snap = {k: h.state()
                               for k, h in list(self._tl.hists.items())}
            return
        if self._seen < self.warmup_chunks:
            return
        stages = self._tl.since(self._snap)
        # Hists delta the same way (HistogramStats.since): chunk 2's
        # out.readback_lag_s sample is compile-sized too (the readback
        # thread blocked behind chunk 1's compile) — read cumulatively
        # it would fire the readback-lagged heuristic on every cold run.
        hists = {k: h.since(self._hist_snap.get(k, {})).report()
                 for k, h in list(self._tl.hists.items())}
        self.recommendation = recommend_from_stages(
            stages, hists, self._current, nint=self._nint
        )
        for k in KNOBS:
            self._tl.gauge(f"tune.rec_{k}",
                           float(self.recommendation.knobs[k]))

    def maybe_persist(self, *, config=None, tuned_nchan: int = 0,
                      **fingerprint_kw) -> Optional[str]:
        """Persist the converged recommendation as an ``online`` profile
        (opt-in: ``BLIT_TUNE_ONLINE=1``); returns the path when written."""
        if self.recommendation is None:
            return None
        if os.environ.get("BLIT_TUNE_ONLINE", "0") != "1":
            return None
        if self.recommendation.knobs == self._current:
            return None  # nothing learned worth persisting
        key, ident = rig_fingerprint(**fingerprint_kw)
        existing = load_profile(key, config)
        if existing is not None and existing.source == "offline":
            # `blit tune` MEASURED those knobs (timed sweep, score_gbps);
            # the online recommendation is a heuristic off one warmup
            # window — possibly a transient load spike.  A measured
            # profile outranks it: never overwrite, or every rig running
            # BLIT_TUNE_ONLINE=1 would silently lose its sweep results.
            log.info("online tuning recommendation %s not persisted: a "
                     "measured offline profile holds key %s",
                     self.recommendation.knobs, key[:24])
            return None
        # Clamp into the exact bounds load_profile enforces (the
        # offline sweep clamps its base the same way): unmoved knobs
        # are copied verbatim from the running reducer, which permits
        # e.g. prefetch_depth=1 — persisting that verbatim would write
        # a profile every subsequent lookup rejects as out-of-range.
        rec = {k: int(self.recommendation.knobs[k]) for k in KNOBS}
        rec["chunk_frames"] = max(self._nint,
                                  min(MAX_CHUNK_FRAMES, rec["chunk_frames"]))
        for k in ("prefetch_depth", "out_depth"):
            rec[k] = max(MIN_DEPTH, min(MAX_DEPTH, rec[k]))
        prof = TuningProfile(
            key=key, rig=ident, source="online",
            trials=self._seen, tuned_nchan=int(tuned_nchan), **rec,
        )
        try:
            return save_profile(prof, config)
        except OSError:  # a read-only rig must not fail the reduction
            log.warning("online tuning profile not writable", exc_info=True)
            return None
