"""Pinned, persistent host staging memory (ISSUE 8 tentpole b).

Every leg of the ingest plane stages bytes through big host buffers: the
chunk rotation's int8 voltage buffers (blit/pipeline.py), the output
plane's readback ring slabs (blit/outplane.py), and the collective
feeds' window planes.  Before this module each stream allocated its
buffers fresh — GB-sized ``np.empty`` calls whose first-touch page
faults land INSIDE the timed stream (BENCH_r05's ingest leg measured
the fault storm, not the disk) and whose pages are cold again for the
next reduction the serve layer runs.  The staging pool makes host
buffers rig-persistent:

- :func:`aligned_empty` allocates page-aligned arrays, so ``readinto``/
  pread paths hit the kernel's aligned fast path and a future pinned
  (``cudaHostRegister``-style) registration has stable addresses to pin.
- :class:`SlabPool` is a process-wide free list keyed by
  ``(shape, dtype)`` under a byte budget: ``take`` reuses an
  already-faulted buffer when one matches (O(1) dict pop), ``give``
  returns a buffer at stream teardown.  Reuse across *streams* — not
  just within one — is the point: the serve layer reduces many
  recordings of the same product shape back to back, and window ``w+1``
  of a scan stages through the slabs window ``w`` just released.

The pool is deliberately dumb: exact shape+dtype match only (a near-miss
realloc is as cheap as the old path), FIFO eviction when over budget,
and counters (``staging.reuse`` / ``staging.alloc`` / ``staging.drop``)
on the process timeline so the hit rate is observable in every telemetry
report.  ``BLIT_STAGING_BYTES`` overrides the budget per process
(``0`` disables pooling entirely — every ``take`` allocates, every
``give`` drops — the A/B lever).
"""

from __future__ import annotations

import os
import threading
from collections import OrderedDict
from typing import Dict, List, Optional, Tuple

import numpy as np

_ALIGN = 4096  # page size: the readinto/pread alignment contract

# Default pool budget: enough for a deep hi-res chunk rotation (a few
# ~100-600 MB chunk buffers) without letting a shape-churning test suite
# hoard RSS.  Per-process; env-overridable.
_DEFAULT_BUDGET = 2 << 30


def aligned_empty(shape, dtype, align: int = _ALIGN) -> np.ndarray:
    """An uninitialized C-contiguous array whose data pointer is
    ``align``-byte aligned (page-aligned by default).  NumPy's own
    allocations guarantee only 16/64-byte alignment; O_DIRECT-grade
    reads and host-memory registration both want pages."""
    dtype = np.dtype(dtype)
    nbytes = int(np.prod(shape, dtype=np.int64)) * dtype.itemsize
    raw = np.empty(nbytes + align, np.uint8)
    off = (-raw.ctypes.data) % align
    # The slice keeps ``raw`` alive via .base — no dangling storage.
    return raw[off:off + nbytes].view(dtype).reshape(shape)


class SlabPool:
    """Process-wide staging-buffer free list (module docstring).

    Thread-safe: producers (BufferRotation fill threads), readback
    threads and consumers all take/give concurrently.  A taken buffer is
    the caller's until given back; the pool never hands one buffer to
    two callers.
    """

    def __init__(self, budget_bytes: Optional[int] = None):
        if budget_bytes is None:
            env = os.environ.get("BLIT_STAGING_BYTES")
            if env is not None:
                budget_bytes = int(env)
            else:
                from blit.config import DEFAULT

                cfg = getattr(DEFAULT, "staging_pool_bytes", None)
                budget_bytes = _DEFAULT_BUDGET if cfg is None else int(cfg)
        self.budget_bytes = budget_bytes
        self._lock = threading.Lock()
        # (shape, dtype.str) -> list of free arrays; OrderedDict gives
        # FIFO key eviction (oldest shape class dropped first).
        self._free: "OrderedDict[Tuple, List[np.ndarray]]" = OrderedDict()
        self._free_bytes = 0
        self.reused = 0
        self.allocated = 0
        self.dropped = 0

    def _count(self, name: str, n: int = 1) -> None:
        try:  # telemetry must never break staging
            from blit import observability

            observability.process_timeline().count(name, n)
        except Exception:  # noqa: BLE001 — counters are best-effort
            pass

    def take(self, shape, dtype=np.int8) -> np.ndarray:
        """A free buffer of exactly ``(shape, dtype)`` — already faulted
        when reused — else a fresh aligned allocation."""
        key = (tuple(shape), np.dtype(dtype).str)
        with self._lock:
            lst = self._free.get(key)
            if lst:
                arr = lst.pop()
                if not lst:
                    del self._free[key]
                self._free_bytes -= arr.nbytes
                self.reused += 1
            else:
                arr = None
                self.allocated += 1
        if arr is None:
            arr = aligned_empty(shape, dtype)
            self._count("staging.alloc")
        else:
            self._count("staging.reuse")
        return arr

    def give(self, arr: Optional[np.ndarray]) -> None:
        """Return a buffer to the pool (dropped when over budget or not
        pool-eligible — non-contiguous views stage nothing)."""
        if arr is None or not arr.flags.c_contiguous:
            return
        key = (arr.shape, arr.dtype.str)
        ndrop = 0
        with self._lock:
            if self.budget_bytes <= 0 or arr.nbytes > self.budget_bytes:
                self.dropped += 1
                ndrop = 1
            else:
                self._free.setdefault(key, []).append(arr)
                self._free_bytes += arr.nbytes
                while self._free_bytes > self.budget_bytes and self._free:
                    # FIFO: evict from the oldest shape class.
                    k, lst = next(iter(self._free.items()))
                    old = lst.pop(0)
                    if not lst:
                        del self._free[k]
                    self._free_bytes -= old.nbytes
                    self.dropped += 1
                    ndrop += 1
        if ndrop:
            # Budget-driven evictions count too: the telemetry counter
            # must agree with stats()["dropped"], or an operator A/B-ing
            # BLIT_STAGING_BYTES via telemetry sees a healthy pool that
            # is actually thrashing.
            self._count("staging.drop", ndrop)

    def stats(self) -> Dict[str, int]:
        with self._lock:
            return {
                "free_bytes": self._free_bytes,
                "free_slabs": sum(len(v) for v in self._free.values()),
                "reused": self.reused,
                "allocated": self.allocated,
                "dropped": self.dropped,
                "budget_bytes": self.budget_bytes,
            }

    def clear(self) -> None:
        with self._lock:
            self._free.clear()
            self._free_bytes = 0


_POOL: Optional[SlabPool] = None
_POOL_LOCK = threading.Lock()


def slab_pool() -> SlabPool:
    """The process-wide staging pool (lazily constructed so the env
    budget is read at first use, not import)."""
    global _POOL
    with _POOL_LOCK:
        if _POOL is None:
            _POOL = SlabPool()
        return _POOL


def _reset_pool() -> None:
    """Drop the global pool (tests re-read the env budget)."""
    global _POOL
    with _POOL_LOCK:
        _POOL = None
