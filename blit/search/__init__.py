"""blit.search — the search plane (ISSUE 6).

On-device Taylor-tree drift-rate search as a first-class product type:
``.hits`` alongside ``.fil``/``.h5``, computed from the same streaming
plane (windowed feeds → pallas/lax drift transform → device-side
threshold + per-band top-k → async ragged hit sink).

- :class:`~blit.search.dedoppler.DedopplerReducer` — the streaming
  driver (search / search_to_file / search_resumable / reduce).
- :class:`~blit.search.hits.Hit` + the array/record codecs — the hit
  product atom and its cache-friendly dense encoding.
- the kernels live in :mod:`blit.ops.pallas_dedoppler`; the ``.hits``
  file writers in :mod:`blit.io.hits`.
"""

from blit.search.dedoppler import DedopplerReducer, SearchCursor
from blit.search.hits import (
    Hit,
    hit_from_record,
    hits_from_array,
    hits_from_packed,
    hits_to_array,
)

__all__ = [
    "DedopplerReducer",
    "SearchCursor",
    "Hit",
    "hit_from_record",
    "hits_from_array",
    "hits_from_packed",
    "hits_to_array",
]
