"""``DedopplerReducer`` — the search plane's streaming driver (ISSUE 6).

RAW voltages → filterbank spectra → Taylor-tree drift search → ``.hits``
products, end to end on the existing planes:

- the INNER reduction is a plain :class:`blit.pipeline.RawReducer`
  (Stokes I, fqav off) — the same pipelined ingest rotation, jitted
  channelizer and async readback every other product rides;
- a :class:`blit.pipeline.BufferRotation` WINDOW FEED re-chunks the
  spectra stream into fixed ``(window_spectra, nchans)`` windows on a
  producer thread (the long-integration windowed-feed shape of ROADMAP
  item 4) — trailing spectra that can't fill a window are dropped,
  deterministically, so resume replays reproduce identical windows;
- each window runs :func:`blit.ops.pallas_dedoppler.dedoppler_hits` on
  device (tree + SNR + threshold + per-band top-k; only the packed hit
  records cross the link), with the packed outputs read back through an
  :class:`blit.outplane.OutputRotation` so window compute, readback and
  hit writing overlap;
- hits stream through :class:`blit.outplane.AsyncSink` write-behind
  into the ``.hits`` writers (blit/io/hits.py) — the ragged sink path.

Determinism contract (tests/test_dedoppler.py): window ``w`` always
covers spectra ``[w·T, (w+1)·T)`` of the gap-free stream, so a resumed
run (``search_resumable`` — skip-windows replay via the reducer's
skip-frames discipline, same rule as ``correlate(acc_frames=)``) and
the sync output path (``BLIT_SYNC_OUTPUT=1`` / ``async_output=False``)
produce BYTE-IDENTICAL ``.hits`` products.

Search knobs left ``None`` resolve from :func:`blit.config.search_defaults`
(SiteConfig fields, overridable per-process via ``BLIT_SEARCH_*`` env).
"""

from __future__ import annotations

import logging
import os
import time
from dataclasses import dataclass, field
from typing import Dict, Iterator, List, Optional, Sequence, Tuple, Union

import numpy as np

from blit import observability
from blit.config import search_defaults
from blit.io.guppi import GuppiRaw, RawSource, open_raw
from blit.io.hits import HitsWriter, ResumableHitsWriter, WindowHits
from blit.observability import Timeline
from blit.ops.pallas_dedoppler import _check_window
from blit.pipeline import BufferRotation, RawReducer, ReductionCursor
from blit.search.hits import HIT_COLS, Hit, hits_from_packed, hits_to_array

log = logging.getLogger("blit.search")


class _Window:
    """A filled search window handed to the consumer; ``view`` aliases
    the rotation buffer until :meth:`release`."""

    __slots__ = ("view", "index", "_idx", "_free")

    def __init__(self, view: np.ndarray, index: int, idx: int, free) -> None:
        self.view = view
        self.index = index
        self._idx = idx
        self._free = free

    def release(self) -> None:
        if self._free is not None:
            free, self._free = self._free, None
            free(self._idx)


@dataclass
class DedopplerReducer:
    """Configured RAW → ``.hits`` drift search (one worker / one chip).

    The filterbank knobs (``nfft``/``ntap``/``nint``/``window``/
    ``dtype``) configure the inner reduction exactly as on
    :class:`~blit.pipeline.RawReducer`; the search knobs bound the
    drift transform and hit extraction.  Every output-affecting knob is
    part of the product fingerprint (:meth:`fingerprint_extra`) and the
    resume identity (:class:`SearchCursor`).
    """

    nfft: int
    ntap: int = 4
    nint: int = 1
    window: str = "hamming"
    fft_method: str = "auto"
    dtype: str = "float32"
    # Search knobs; None -> blit.config.search_defaults() (SiteConfig +
    # BLIT_SEARCH_* env overrides).
    window_spectra: Optional[int] = None
    top_k: Optional[int] = None
    snr_threshold: Optional[float] = None
    max_drift_bins: Optional[int] = None
    # Drift-transform backend (blit/ops/pallas_dedoppler): "reference" |
    # "pallas" | "auto"; interpret=True runs the pallas kernel on CPU.
    kernel: str = "auto"
    interpret: bool = False
    # None = the rig's tuning profile via the inner RawReducer
    # (blit/tune.py), else the RawReducer defaults.
    prefetch_depth: Optional[int] = None
    out_depth: Optional[int] = None
    chunk_frames: Optional[int] = None
    timeline: Timeline = field(default_factory=Timeline)
    # Async planes (window feed readback + write-behind hit sink);
    # False — or BLIT_SYNC_OUTPUT=1 — restores the serialized path with
    # byte-identical products (the A/B lever, as on RawReducer).
    async_output: bool = True
    output_stall_timeout_s: Optional[float] = None

    # Fixed facets of the search reduction (the fingerprint reads these
    # off the reducer like any other).
    stokes = "I"
    fqav_by = 1

    def __post_init__(self):
        if os.environ.get("BLIT_SYNC_OUTPUT"):
            self.async_output = False
        d = search_defaults()
        if self.window_spectra is None:
            self.window_spectra = d["window_spectra"]
        if self.top_k is None:
            self.top_k = d["top_k"]
        if self.snr_threshold is None:
            self.snr_threshold = d["snr_threshold"]
        if self.max_drift_bins is None:
            self.max_drift_bins = d["max_drift_bins"]
        if self.max_drift_bins is not None and self.max_drift_bins < 0:
            # The -1 "no limit" header/cursor encoding round-trips back
            # to unlimited (a literal negative limit would mask every
            # drift row and report zero hits without erroring).
            self.max_drift_bins = None
        _check_window(self.window_spectra)
        self._red = RawReducer(
            nfft=self.nfft, ntap=self.ntap, nint=self.nint, stokes="I",
            window=self.window, fft_method=self.fft_method,
            dtype=self.dtype, prefetch_depth=self.prefetch_depth,
            out_depth=self.out_depth,
            chunk_frames=self.chunk_frames, timeline=self.timeline,
            async_output=self.async_output,
            output_stall_timeout_s=self.output_stall_timeout_s,
        )
        # The inner reducer resolved the knobs (profile or default) —
        # mirror them so this reducer's own rotation depths agree.
        self.prefetch_depth = self._red.prefetch_depth
        self.out_depth = self._red.out_depth
        if self.chunk_frames is None:
            self.chunk_frames = self._red.chunk_frames

    def tuning_provenance(self) -> Dict:
        """Delegated to the inner RawReducer (the knobs are its)."""
        return self._red.tuning_provenance()

    # -- identity ----------------------------------------------------------
    def fingerprint_extra(self) -> Dict:
        """The search-specific fingerprint components
        (:func:`blit.serve.cache.fingerprint_for` merges them into the
        content address; nbands derives from the raw input + nfft, both
        already in the key)."""
        return {
            "product_kind": "hits",
            "window_spectra": int(self.window_spectra),
            "top_k": int(self.top_k),
            "snr_threshold": float(self.snr_threshold),
            "max_drift_bins": (
                None if self.max_drift_bins is None
                else int(self.max_drift_bins)
            ),
        }

    # -- headers -----------------------------------------------------------
    def header_for(self, raw: GuppiRaw) -> Dict:
        """The search product header: the inner filterbank header plus
        the search knobs (everything a ``.hits`` consumer needs to map
        bins back to sky frequency / drift rate)."""
        hdr = self._red.header_for(raw)
        hdr.update(
            search_window_spectra=int(self.window_spectra),
            search_top_k=int(self.top_k),
            search_snr_threshold=float(self.snr_threshold),
            search_max_drift_bins=(
                -1 if self.max_drift_bins is None
                else int(self.max_drift_bins)
            ),
            search_nbands=self._nbands(hdr["nchans"]),
        )
        # The kernel choice is deliberately NOT in the header (or the
        # fingerprint/cursor identity): reference and pallas produce
        # bitwise-identical sums by construction, so the product bytes
        # must not fork on how they were computed.
        return hdr

    def _nbands(self, nchans: int) -> int:
        """Per-band top-k granularity: one band per coarse channel (the
        natural unit frequency is sharded by everywhere else); a channel
        count that isn't coarse-aligned searches as a single band."""
        return nchans // self.nfft if nchans % self.nfft == 0 else 1

    def _open_validated(self, raw_src: RawSource) -> Tuple[GuppiRaw, Dict]:
        raw = open_raw(raw_src)
        if raw.nblocks == 0:
            raise ValueError(f"empty or fully truncated RAW file: {raw.path}")
        return raw, self.header_for(raw)

    # -- window feed -------------------------------------------------------
    def _producer(self, raw: GuppiRaw, skip_windows: int, nchans: int,
                  bufs: List[Optional[np.ndarray]],
                  rot: BufferRotation) -> None:
        """Fill the window rotation from the inner reducer's spectra
        stream (producer thread).  Window ``w`` holds spectra
        ``[w·T, (w+1)·T)`` of the gap-free stream; a trailing partial
        window is dropped (deterministic across resumes)."""
        T = self.window_spectra
        cur: Optional[int] = None
        filled = 0
        widx = skip_windows
        skip_frames = skip_windows * T * self.nint
        for slab in self._red.stream(raw, skip_frames=skip_frames):
            data = slab[:, 0, :]  # Stokes-I plane: (nspectra, nchans)
            pos = 0
            n = data.shape[0]
            while pos < n:
                if cur is None:
                    cur = rot.acquire()
                    if cur is None:
                        return  # consumer abandoned the stream
                    if bufs[cur] is None:
                        bufs[cur] = np.empty((T, nchans), np.float32)
                    filled = 0
                take = min(T - filled, n - pos)
                with self.timeline.stage("search.window_fill",
                                         nbytes=take * nchans * 4):
                    bufs[cur][filled:filled + take] = data[pos:pos + take]
                filled += take
                pos += take
                if filled == T:
                    rot.emit(cur, widx)
                    widx += 1
                    cur = None

    def _windows(self, raw: GuppiRaw, skip_windows: int, nchans: int,
                 extra_slots: int = 0) -> Iterator[_Window]:
        """The pipelined window feed behind the search loop — the
        :meth:`RawReducer._chunks` shape one level up: the consumer MUST
        ``release()`` every window once nothing still reads its buffer."""
        nbufs = max(2, self.prefetch_depth) + max(0, extra_slots)
        bufs: List[Optional[np.ndarray]] = [None] * nbufs
        rot = BufferRotation(
            nbufs,
            lambda r: self._producer(raw, skip_windows, nchans, bufs, r),
            name="blit-search-feed",
        )
        try:
            for idx, widx in rot.slots():
                yield _Window(bufs[idx], widx, idx, rot.release)
        finally:
            # No cross-call buffer cache (unlike RawReducer's chunk
            # ring): window buffers can run to GBs at wide products and
            # service/CLI callers build a fresh reducer per request —
            # retaining them would pin memory for a reuse that never
            # comes.  `bufs` frees with this frame.
            rot.close()

    # -- device step -------------------------------------------------------
    def _jitted(self, nbands: int):
        """The per-window search step with this reducer's knobs bound.
        ``dedoppler_hits`` is jitted at module level with the knobs
        static, so compilations cache process-wide — a fresh reducer per
        service request (the ProductService pattern) reuses the compiled
        program instead of re-tracing the unrolled tree."""
        import functools

        from blit.ops.pallas_dedoppler import dedoppler_hits

        return functools.partial(
            dedoppler_hits, top_k=self.top_k, nbands=nbands,
            max_drift_bins=self.max_drift_bins, kernel=self.kernel,
            interpret=self.interpret,
        )

    # -- the search stream -------------------------------------------------
    def _search_stream(
        self, raw: GuppiRaw, hdr: Dict, skip_windows: int = 0
    ) -> Iterator[Tuple[int, List[Hit]]]:
        """Yield ``(window_index, hits)`` in stream order.  On the async
        plane the packed device outputs read back on the OutputRotation
        thread while the next window dispatches; the sync fallback times
        each tree step directly (the ``search.tree_s`` histogram)."""
        import jax
        import jax.numpy as jnp

        nchans = hdr["nchans"]
        nbands = self._nbands(nchans)
        jfn = self._jitted(nbands)
        thr = np.float32(self.snr_threshold)

        def decode(packed: np.ndarray, widx: int) -> List[Hit]:
            hits = hits_from_packed(packed, widx, hdr)
            self.timeline.observe("search.hits_per_window", len(hits))
            return hits

        with observability.span(
            "search.stream", nfft=self.nfft, windows=self.window_spectra,
            path=getattr(raw, "path", ""),
        ):
            if not self.async_output:
                for win in self._windows(raw, skip_windows, nchans):
                    try:
                        with observability.span("search.window",
                                                window=win.index):
                            t0 = time.perf_counter()
                            packed = jfn(jnp.asarray(win.view), thr)
                            packed = np.asarray(
                                jax.block_until_ready(packed))
                            self.timeline.observe(
                                "search.tree_s",
                                time.perf_counter() - t0)
                    finally:
                        win.release()
                    yield win.index, decode(packed, win.index)
                return

            from blit.outplane import OutputRotation, readback_extra_slots

            depth = max(2, self.out_depth)
            rot = OutputRotation(
                depth=depth, timeline=self.timeline,
                reuse=False, name="blit-search-readback",
                stall_timeout_s=self.output_stall_timeout_s,
            )
            try:
                extra = readback_extra_slots(depth, self.prefetch_depth)
                for win in self._windows(raw, skip_windows, nchans,
                                         extra_slots=extra):
                    with self.timeline.stage("dispatch", byte_free=True):
                        packed = jfn(jnp.asarray(win.view), thr)
                    for slab in rot.put(packed, nbytes=win.view.nbytes,
                                        payload=win.index,
                                        on_consumed=win.release):
                        yield slab.payload, decode(slab.data, slab.payload)
                        slab.release()
                for slab in rot.drain():
                    yield slab.payload, decode(slab.data, slab.payload)
                    slab.release()
            finally:
                rot.close()

    # -- whole-recording entry points --------------------------------------
    def search(self, raw_src: RawSource) -> Tuple[Dict, List[Hit]]:
        """Search a whole RAW recording (file / ``.NNNN.raw`` sequence)
        in memory → ``(header, hits)`` in window order."""
        raw, hdr = self._open_validated(raw_src)
        hits: List[Hit] = []
        windows = 0
        with observability.span("search", nfft=self.nfft):
            for _, hs in self._search_stream(raw, hdr):
                hits.extend(hs)
                windows += 1
        hdr["search_windows"] = windows
        hdr["search_nhits"] = len(hits)
        return hdr, hits

    def reduce(self, raw_src: RawSource) -> Tuple[Dict, np.ndarray]:
        """The ProductService entry point: like :meth:`search` but the
        hit list comes back as the dense float32 encoding
        (:func:`blit.search.hits.hits_to_array`) under a slab-shaped
        header — so ``.hits`` products flow through the content-addressed
        cache, single-flight coalescing and the disk tier unchanged."""
        hdr, hits = self.search(raw_src)
        arr = hits_to_array(hits)
        hdr = dict(hdr)
        # The cache's disk tier (FBH5) stores (nsamps, nifs, nchans)
        # slabs; the encoded hit table IS one, with the real channel
        # count parked under search_nchans.
        hdr["search_nchans"] = hdr["nchans"]
        hdr.update(nchans=HIT_COLS, nifs=1, nsamps=len(hits))
        return hdr, arr

    def _pump(self, raw: GuppiRaw, hdr: Dict, writer,
              skip_windows: int = 0) -> int:
        """Drive the search stream into a ``.hits`` writer — write-behind
        through :class:`~blit.outplane.AsyncSink` on the async plane —
        and finalize it.  Returns hits written this run.  On error the
        writer ``abort()``s (its own crash contract) and the error
        re-raises.  Runs under :func:`blit.monitor.publishing` like
        :meth:`blit.pipeline.RawReducer._pump` (ISSUE 11)."""
        from blit.monitor import publishing

        with publishing(self.timeline):
            return self._pump_impl(raw, hdr, writer, skip_windows)

    def _pump_impl(self, raw: GuppiRaw, hdr: Dict, writer,
                   skip_windows: int = 0) -> int:
        if not self.async_output:
            try:
                for widx, hits in self._search_stream(raw, hdr,
                                                      skip_windows):
                    writer.append(WindowHits(widx, hits))
                writer.close()
            except BaseException:
                writer.abort()
                raise
            return writer.nsamps

        from blit.outplane import AsyncSink

        sink = AsyncSink(
            writer, depth=max(2, self.out_depth),
            timeline=self.timeline,
            stall_timeout_s=self.output_stall_timeout_s,
        )
        try:
            for widx, hits in self._search_stream(raw, hdr, skip_windows):
                sink.append(WindowHits(widx, hits))
            sink.close()
        except BaseException:
            sink.abort()
            raise
        return sink.nsamps

    def search_to_file(self, raw_src: RawSource, out_path: str) -> Dict:
        """Search and write a ``.hits`` product (atomic ``.partial``
        publish; byte-identical between the sync and async planes)."""
        raw, hdr = self._open_validated(raw_src)
        w = HitsWriter(out_path, hdr)
        with observability.span("search.to_file", out=out_path):
            hdr["search_nhits"] = self._pump(raw, hdr, w)
        hdr["search_windows"] = w.nwindows
        return hdr

    def search_resumable(self, raw_src: RawSource, out_path: str) -> Dict:
        """Search to a ``.hits`` product with crash-resumable streaming:
        a :class:`SearchCursor` sidecar claims each window AFTER its
        lines are durable; a re-run resumes at the claimed window
        boundary via the skip-windows replay and reproduces the exact
        remaining hit lines (the finished product is byte-identical to
        an uninterrupted run)."""
        raw, hdr = self._open_validated(raw_src)
        paths = getattr(raw, "paths", None) or raw.path
        cur = SearchCursor.load(out_path)
        resuming = (
            cur is not None
            and cur.matches(self, paths)
            and os.path.exists(out_path)
        )
        if resuming and os.path.getsize(out_path) < cur.byte_offset:
            # A cursor claiming more bytes than the file holds (crash-
            # corrupted or replaced product): POSIX truncate would EXTEND
            # the file with a NUL hole and the finished product would be
            # unreadable — start fresh instead, the resume_target_ok
            # discipline (blit/pipeline.py) for the ragged format.
            log.warning(
                "resume target %s is shorter than the cursor's claimed "
                "%d bytes (crash-corrupted?); discarding %d claimed "
                "windows and starting fresh",
                out_path, cur.byte_offset, cur.windows_done,
            )
            resuming = False
        if resuming:
            # Content verification of the claim (ISSUE 13): the byte-
            # length probe above cannot see a flipped byte INSIDE the
            # claimed lines or a tampered sidecar — the manifest's claim
            # ledger can.  False = fail closed (fresh start); a product
            # without a manifest keeps the length-only behavior.
            from blit import integrity

            if integrity.verify_claim(out_path, cur.windows_done,
                                      fmt="hits") is False:
                log.warning(
                    "resume target %s fails its claimed-region digest "
                    "(torn write or tampered sidecar); discarding %d "
                    "claimed windows and starting fresh",
                    out_path, cur.windows_done,
                )
                resuming = False
        if resuming:
            log.info("resuming %s at window %d", out_path, cur.windows_done)
        else:
            size, mtime_ns = ReductionCursor.stat_raw(paths)
            cur = SearchCursor(
                paths, self.nfft, self.ntap, self.nint,
                window=self.window, dtype=self.dtype,
                window_spectra=self.window_spectra, top_k=self.top_k,
                snr_threshold=float(self.snr_threshold),
                max_drift_bins=(
                    -1 if self.max_drift_bins is None
                    else int(self.max_drift_bins)
                ),
                raw_size=size, raw_mtime_ns=mtime_ns,
            )
        skip = cur.windows_done if resuming else 0
        w = ResumableHitsWriter(out_path, hdr, skip, cur)
        with observability.span("search.resumable", out=out_path,
                                resumed=bool(resuming)):
            self._pump(raw, hdr, w, skip_windows=skip)
        hdr["search_windows"] = w.nwindows
        hdr["search_nhits"] = w.nsamps
        return hdr


@dataclass
class SearchCursor:
    """Restart state for a streaming drift search, persisted as a JSON
    sidecar next to the ``.hits`` product (the
    :class:`blit.pipeline.ReductionCursor` discipline, windowed).

    ``windows_done`` counts search windows fully extracted *and
    durable*; ``byte_offset`` is the product file length those windows
    claim — resume truncates to it, dropping any un-checkpointed tail.
    Identity guards cover the raw bytes (order-insensitive member
    triples) and every output-affecting knob, filterbank and search
    alike."""

    raw_path: Union[str, List[str]]
    nfft: int
    ntap: int
    nint: int
    window: str = "hamming"
    dtype: str = "float32"
    window_spectra: int = 64
    top_k: int = 8
    snr_threshold: float = 10.0
    max_drift_bins: int = -1
    windows_done: int = 0
    hits_done: int = 0
    byte_offset: int = 0
    raw_size: Union[int, List[int]] = -1
    raw_mtime_ns: Union[int, List[int]] = -1
    # Per-window ``[window, byte_offset, hits]`` claims, appended as
    # each window is claimed (ISSUE 12): windows are RAGGED — a
    # zero-hit window leaves no line — so a resume at an EARLIER window
    # than this cursor's own claim (the sharded plane's pod-wide-agreed
    # minimum) can only find its truncation point here.  The ledger is
    # BOUNDED (blit/io/hits.py trims to the newest CLAIM_LEDGER_MAX
    # entries — per-append cursor I/O must not grow with session
    # length); a window older than the tail resolves to None and that
    # player restarts fresh.  None (pre-existing sidecars) = resumable
    # only at the exact claimed window, the old behavior.
    window_claims: Optional[List[List[int]]] = None

    def claim_at(self, windows: int) -> Optional[Tuple[int, int]]:
        """The ``(byte_offset, hits_done)`` claim after ``windows`` full
        windows, when this cursor recorded it (``windows`` == the full
        claim always resolves; earlier windows need a ``window_claims``
        ledger entry) — :func:`blit.io.hits.ledger_claim_at`, the rule
        shared with :class:`blit.stream.cursor.StreamCursor`."""
        from blit.io.hits import ledger_claim_at

        return ledger_claim_at(windows, self.windows_done,
                               self.byte_offset, self.hits_done,
                               self.window_claims)

    # One sidecar persistence protocol, shared with the pipeline cursor
    # (ReductionCursor's save/load operate on self.__dict__ / cls(**...),
    # so they bind cleanly here) — a durability fix there reaches the
    # search plane automatically.
    path_for = staticmethod(ReductionCursor.path_for)
    save = ReductionCursor.save
    load = classmethod(ReductionCursor.load.__func__)

    def matches(self, red: DedopplerReducer,
                raw_path: Union[str, Sequence[str]]) -> bool:
        try:
            size, mtime_ns = ReductionCursor.stat_raw(raw_path)
        except OSError:
            return False
        return (
            ReductionCursor.normalized_members(
                self.raw_path, self.raw_size, self.raw_mtime_ns)
            == ReductionCursor.normalized_members(raw_path, size, mtime_ns)
            and self.nfft == red.nfft
            and self.ntap == red.ntap
            and self.nint == red.nint
            and self.window == red.window
            and self.dtype == red.dtype
            and self.window_spectra == red.window_spectra
            and self.top_k == red.top_k
            and self.snr_threshold == float(red.snr_threshold)
            and self.max_drift_bins == (
                -1 if red.max_drift_bins is None else int(red.max_drift_bins)
            )
        )
