"""Hit records — the search plane's product atom.

A :class:`Hit` is one ``(drift rate, frequency)`` cell that survived the
device-side threshold + per-band top-k of
:func:`blit.ops.pallas_dedoppler.dedoppler_hits`: bin-space coordinates
(drift bins per window, absolute fine-channel index) plus the physical
values derived from the filterbank header (sky frequency in MHz, drift
rate in Hz/s), the SNR/power that ranked it, and provenance (which time
window of which search, anchored at which spectrum).

Two wire encodings, both deterministic:

- JSON-line records (:meth:`Hit.record` / :func:`hit_from_record`) —
  the ``.hits`` product format (blit/io/hits.py);
- a dense float32 array (:func:`hits_to_array` /
  :func:`hits_from_array`) shaped ``(nhits, 1, HIT_COLS)`` — the
  3-D slab shape the product cache's FBH5 disk tier already speaks, so
  ``.hits`` products ride :class:`blit.serve.cache.ProductCache`
  (fingerprints, atomic publish, corruption probes) unchanged.  Fine
  channel indices are split into two exact-in-f32 halves
  (``chan = hi·2**16 + lo``) because the hi-res product's 2^26 channels
  exceed float32's 2^24 integer range.
"""

from __future__ import annotations

from dataclasses import asdict, dataclass
from typing import Dict, List, Sequence

import numpy as np

from blit.ops.pallas_dedoppler import unpack_hits

# Columns of the dense encoding (:func:`hits_to_array`):
# [snr, power, drift_bins, chan_hi, chan_lo, band, window, reserved].
HIT_COLS = 8
_CHAN_SPLIT = 1 << 16


@dataclass(frozen=True)
class Hit:
    """One detected drift-rate candidate (module docstring)."""

    snr: float
    power: float
    drift_bins: int
    chan: int
    band: int
    window: int
    t_start: int
    freq_mhz: float
    drift_hz_s: float

    def record(self) -> Dict:
        """The JSON-safe record of this hit (plain builtins only)."""
        return asdict(self)


def hit_from_record(rec: Dict) -> Hit:
    """Rebuild a :class:`Hit` from :meth:`Hit.record` output."""
    return Hit(
        snr=float(rec["snr"]), power=float(rec["power"]),
        drift_bins=int(rec["drift_bins"]), chan=int(rec["chan"]),
        band=int(rec["band"]), window=int(rec["window"]),
        t_start=int(rec["t_start"]), freq_mhz=float(rec["freq_mhz"]),
        drift_hz_s=float(rec["drift_hz_s"]),
    )


def physical(chan: int, drift_bins: int, header: Dict) -> tuple:
    """``(freq_mhz, drift_hz_s)`` of a bin-space hit under ``header``
    (a filterbank header carrying ``fch1``/``foff`` in MHz, ``tsamp`` in
    seconds, and ``search_window_spectra``).  One shared function so
    every decode path produces identical doubles."""
    T = int(header["search_window_spectra"])
    freq_mhz = float(header["fch1"]) + chan * float(header["foff"])
    drift_hz_s = (
        drift_bins * float(header["foff"]) * 1e6
        / ((T - 1) * float(header["tsamp"]))
    )
    return freq_mhz, drift_hz_s


def hits_from_packed(
    packed: np.ndarray, window: int, header: Dict
) -> List[Hit]:
    """Decode one window's fetched ``dedoppler_hits`` array into
    :class:`Hit` objects (device-side threshold sentinels dropped; order
    preserved: band-major, SNR-descending within a band)."""
    T = int(header["search_window_spectra"])
    snr, power, drift, chan, band = unpack_hits(packed)
    out = []
    for i in range(len(snr)):
        c, d = int(chan[i]), int(drift[i])
        freq_mhz, drift_hz_s = physical(c, d, header)
        out.append(Hit(
            snr=float(snr[i]), power=float(power[i]), drift_bins=d,
            chan=c, band=int(band[i]), window=int(window),
            t_start=int(window) * T, freq_mhz=freq_mhz,
            drift_hz_s=drift_hz_s,
        ))
    return out


def hits_to_array(hits: Sequence[Hit]) -> np.ndarray:
    """Dense cache encoding: ``(nhits, 1, HIT_COLS)`` float32 (module
    docstring).  Bin-space fields only — the physical values re-derive
    from the header on decode, so the encoding stays exact."""
    out = np.zeros((len(hits), 1, HIT_COLS), np.float32)
    for i, h in enumerate(hits):
        out[i, 0] = (
            np.float32(h.snr), np.float32(h.power), h.drift_bins,
            h.chan // _CHAN_SPLIT, h.chan % _CHAN_SPLIT, h.band,
            h.window, 0.0,
        )
    return out


def hits_from_array(arr: np.ndarray, header: Dict) -> List[Hit]:
    """Decode :func:`hits_to_array` output back into :class:`Hit`
    objects under ``header`` (the search product header)."""
    T = int(header["search_window_spectra"])
    out = []
    for row in np.asarray(arr).reshape(-1, HIT_COLS):
        chan = int(row[3]) * _CHAN_SPLIT + int(row[4])
        drift = int(row[2])
        freq_mhz, drift_hz_s = physical(chan, drift, header)
        out.append(Hit(
            snr=float(np.float32(row[0])), power=float(np.float32(row[1])),
            drift_bins=drift, chan=chan, band=int(row[5]),
            window=int(row[6]), t_start=int(row[6]) * T,
            freq_mhz=freq_mhz, drift_hz_s=drift_hz_s,
        ))
    return out
