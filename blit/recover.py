"""The crash-recovery plane (ISSUE 12): supervised sharded scans,
mesh degrade-and-resume, live-session rejoin supervision, chaos drills.

The reference package's whole design assumes a 64-node recorder cluster
where nodes die mid-session (MacMahon+ 2018, arXiv:1707.06024), yet the
two newest planes are the two most fragile: the sharded scan (ISSUE 9)
is ONE SPMD program whose collectives hang forever if any pod peer
dies, and a live stream consumer (ISSUE 7) that restarts used to lose
the whole session.  PR 2 gave the *pool* path retries, breakers and
respawn; this module extends that fault-tolerance contract to the
sharded and streaming planes:

- **detection** — every supervised pod process refreshes a per-process
  :class:`Lease` file beside the products *between windows* (the
  ``heartbeat=`` hook of the sharded entry points), so a peer that dies
  (SIGKILL — no farewell) or wedges (hung collective, injected
  ``hang``) stops beating and the :class:`ScanSupervisor` detects it
  from OUTSIDE the SPMD program within the lease TTL — instead of the
  surviving peers blocking in ICI forever.  The in-process twin of the
  lease is :class:`blit.observability.StallWatchdog`; a lease IS a
  stall watchdog whose beat crosses a process boundary through mtime.

- **degrade-and-resume** — on detection the supervisor SIGKILLs the
  rest of the attempt (clean abort: the resumable writers fsync data
  before their cursors claim it, so files + cursors ARE the restart
  state), re-plans via :func:`replan` — a reshaped ``(band, bank)``
  pod over the surviving hosts when every process can still own whole
  band rows, else automatic fallback to the PR 2 pool path — and
  resumes from :class:`~blit.pipeline.ReductionCursor` /
  :class:`~blit.search.dedoppler.SearchCursor`, byte-identical to an
  uninterrupted run (the pool oracle pins products; the chaos drills
  pin supervised restarts).

- **live-session rejoin** — :class:`StreamSupervisor` restarts a
  killed/hung live consumer against the still-recording session with
  ``resume=True`` (the :class:`blit.stream.cursor.StreamCursor`
  sidecar), producing the same bytes as a never-restarted consumer.

- **chaos drills** — the ``BLIT_FAULTS`` grammar's ``kill``/``hang``
  modes (blit/faults.py) at the ``mesh.window`` / ``stream.chunk``
  injection points, driven end-to-end by ``blit chaos`` (run a seeded
  kill/hang schedule against a real multi-process scan or live stream,
  assert recovery + byte-identity) and ``ingest-bench --chaos``.

Telemetry: ``recover.detect_s`` / ``recover.resume_s`` histograms and
``recover.*`` counters land on the supervisor's Timeline (published
live under ISSUE 11, rendered by ``blit top``); a mid-recovery
supervisor degrades ``/healthz`` through the monitor health hooks.

This module imports jax only inside the execution legs — planning,
leases and the supervisor watch loop stay import-light so ``blit
chaos`` can orchestrate without paying the jax import in the parent.
"""

from __future__ import annotations

import itertools
import json
import logging
import os
import signal
import socket
import subprocess
import sys
import threading
import time
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Tuple

from blit.config import DEFAULT, SiteConfig, recover_defaults
from blit.observability import StallWatchdog, Timeline, hostname

log = logging.getLogger("blit.recover")

# The recovery plane's latency histograms (the MESH_HISTS convention):
# detection latency (death/wedge → supervisor notices) and recovery
# latency (detection → the re-planned attempt makes its first progress).
RECOVER_HISTS = ("recover.detect_s", "recover.resume_s")


# -- leases ------------------------------------------------------------------


class Lease:
    """One process's heartbeat lease: a small JSON file refreshed
    between windows whose MTIME is the liveness signal (content is
    diagnostics — pid/host/window).  Atomic tmp+replace writes, so a
    reader never parses a torn lease; a SIGKILLed process simply stops
    refreshing and the file goes stale — which is the point."""

    def __init__(self, lease_dir: str, proc: int):
        os.makedirs(lease_dir, exist_ok=True)
        self.path = self.path_for(lease_dir, proc)
        self.proc = proc
        self._n = 0

    @staticmethod
    def path_for(lease_dir: str, proc: int) -> str:
        return os.path.join(lease_dir, f"proc{proc}.lease")

    def beat(self, window: int = -1) -> None:
        self._n += 1
        tmp = self.path + ".tmp"
        with open(tmp, "w") as f:
            json.dump({"proc": self.proc, "pid": os.getpid(),
                       "host": hostname(), "window": int(window),
                       "n": self._n}, f)
        os.replace(tmp, self.path)


def lease_age_s(lease_dir: str, proc: int,
                now: Optional[float] = None) -> Optional[float]:
    """Seconds since ``proc`` last beat its lease; None before the
    first beat (bring-up — judged against the grace budget instead)."""
    try:
        mtime = os.stat(Lease.path_for(lease_dir, proc)).st_mtime
    except OSError:
        return None
    return (time.time() if now is None else now) - mtime


def read_lease(lease_dir: str, proc: int) -> Optional[Dict]:
    try:
        with open(Lease.path_for(lease_dir, proc)) as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


# Per-process lease-dir disambiguator: two supervisors sharing an
# output directory must never beat/clear each other's lease files.
_RUN_SEQ = itertools.count()


def _unique_lease_dir(base: str) -> str:
    return os.path.join(base, ".blit-lease",
                        f"run-{os.getpid()}-{next(_RUN_SEQ)}")


class _LeaseWatch:
    """One child's liveness, judged by a
    :class:`~blit.observability.StallWatchdog` whose beat is the lease
    file's observed mtime CHANGE — the in-process stall discipline
    reused across the process boundary, with the lease as the beat
    transport (staleness math, detection-latency reporting and the
    armed/unarmed contract all stay the watchdog's).

    Warm-up: the TTL is only armed once ``_WARM_BEATS`` beats have
    landed — the bring-up beat plus the first windows, so the first
    window's one-off jit compile (20-40 s on a real TPU) is judged
    against the GRACE budget like distributed init, not the
    steady-state lease TTL.  (The remaining uncovered gap is the
    post-last-window drain/close: size ``lease_ttl_s`` above the
    worst per-window AND finalization time for the product shape.)"""

    _WARM_BEATS = 3

    def __init__(self, lease_dir: str, proc: int, ttl_s: float,
                 grace_s: Optional[float] = None):
        self.lease_dir = lease_dir
        self.proc = proc
        self._ttl_s = ttl_s
        self._grace_s = max(grace_s or ttl_s, ttl_s)
        self.wd = StallWatchdog(
            self._grace_s, f"blit-recover-proc{proc}",
            what="a dead or wedged pod peer stops refreshing its lease",
        )
        self._mtime: Optional[float] = None
        self._beats = 0
        self.seen = False

    def observe(self) -> None:
        """One supervisor poll: stat the lease, beat on change."""
        try:
            m = os.stat(
                Lease.path_for(self.lease_dir, self.proc)).st_mtime
        except OSError:
            return
        if m != self._mtime:
            self._mtime = m
            self.wd.beat()
            self.seen = True
            self._beats += 1
            if self._beats >= self._WARM_BEATS:
                self.wd.timeout_s = self._ttl_s

    def stalled(self) -> bool:
        return self.seen and self.wd.stalled()

    def fresh(self) -> bool:
        """Beating and not stale — the elastic controller's standby
        admissibility check (ISSUE 17): a standby is only worth a warm
        handoff when its lease is live RIGHT NOW."""
        return self.seen and not self.wd.stalled()

    def age_s(self) -> float:
        return self.wd.age_s()


# Public alias (ISSUE 14): the fleet front door watches its serving
# peers with the SAME lease discipline the scan supervisor watches pod
# children — one staleness contract for "a process stopped making
# progress", whatever the process serves.  Peer processes beat a
# :class:`Lease` in the fleet's lease dir (bring-up beat + one per
# request/heartbeat tick); the door runs a LeaseWatch per peer and
# ejects from the consistent-hash ring on expiry.
LeaseWatch = _LeaseWatch


# -- planning ----------------------------------------------------------------


@dataclass(frozen=True)
class ScanPlan:
    """One attempt's execution shape: ``mode="sharded"`` runs the scan
    as a ``nprocs``-process pod (each child forcing
    ``devices_per_proc`` host devices — whole band rows per process),
    ``mode="pool"`` falls back to the PR 2 per-player pool path."""

    mode: str  # "sharded" | "pool"
    nprocs: int = 0
    devices_per_proc: int = 0


def replan(nband: int, nbank: int, devices_per_proc: Optional[int],
           alive_procs: int) -> ScanPlan:
    """Re-plan a ``(nband, nbank)`` scan over ``alive_procs`` surviving
    hosts of ``devices_per_proc`` chips each (ISSUE 12 tentpole).

    The sharded plane needs ``nband*nbank`` mesh devices and — because
    each band's product is written by its bank-0 chip's owner and the
    per-process feed opens whole players — every process must own WHOLE
    band rows.  The largest process count ``p <= alive_procs`` with
    ``p`` dividing the mesh, ``nbank`` dividing the per-process share,
    and the share fitting on a host wins (most surviving parallelism);
    when no such ``p`` exists (too few chips survive) the plan degrades
    to the pool path, which needs no mesh at all."""
    need = nband * nbank
    cap = devices_per_proc if devices_per_proc else need
    for p in range(min(max(alive_procs, 0), need), 0, -1):
        if need % p:
            continue
        share = need // p
        if share % nbank:
            continue  # a process would split a band row
        if share > cap:
            continue  # more chips than a surviving host has
        return ScanPlan("sharded", p, share)
    return ScanPlan("pool")


# -- /healthz integration ----------------------------------------------------

_ACTIVE: Dict[int, Dict] = {}
_ACTIVE_LOCK = threading.Lock()


def _health_state() -> Optional[Dict]:
    """The monitor health hook: degraded while ANY supervisor on this
    process is mid-recovery (between detecting a failure and the
    re-planned attempt completing)."""
    with _ACTIVE_LOCK:
        recovering = [s for s in _ACTIVE.values()
                      if s.get("phase") == "recovering"]
        if not recovering:
            return None
        s = recovering[0]
        return {"degraded": True,
                "reason": (f"attempt{s.get('attempt')}-"
                           f"{s.get('plan', '?')}"),
                "supervisors": len(recovering)}


def active_supervisors() -> List[Dict]:
    """Snapshot of every live supervisor's state (the ``/healthz``
    detail and the ``blit chaos`` progress surface)."""
    with _ACTIVE_LOCK:
        return [dict(s) for s in _ACTIVE.values()]


def _register(state: Dict) -> int:
    from blit import monitor

    with _ACTIVE_LOCK:
        key = id(state)
        _ACTIVE[key] = state
        monitor.register_health_hook("recover", _health_state)
    return key


def _unregister(key: int) -> None:
    from blit import monitor

    # Register/unregister run UNDER the registry lock so a finishing
    # supervisor can never unhook a newly-started one (pop, observe
    # empty, lose the race to a fresh _register, then unhook it).
    with _ACTIVE_LOCK:
        _ACTIVE.pop(key, None)
        if not _ACTIVE:
            monitor.unregister_health_hook("recover")


# -- child processes ---------------------------------------------------------


def _free_port() -> str:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return str(port)


def _spawn_child(spec: Dict, spec_path: str, env: Dict[str, str],
                 log_stem: str) -> subprocess.Popen:
    """One supervised child: ``python -m blit.recover <spec.json>``,
    output redirected to files (a chatty distributed bring-up can fill
    a 64 KiB pipe and wedge the child — the PR 8 deflake discipline)."""
    with open(spec_path, "w") as f:
        json.dump(spec, f)
    # The child must import THIS blit, installed or not (test checkouts
    # run uninstalled with the repo root on the parent's sys.path only).
    import blit

    repo = os.path.dirname(os.path.dirname(os.path.abspath(
        blit.__file__)))
    env = dict(env)
    env["PYTHONPATH"] = os.pathsep.join(
        [repo] + [p for p in env.get("PYTHONPATH", "").split(os.pathsep)
                  if p]
    )
    fo = open(log_stem + ".out", "w")
    fe = open(log_stem + ".err", "w")
    p = subprocess.Popen(
        [sys.executable, "-m", "blit.recover", spec_path],
        env=env, stdout=fo, stderr=fe, text=True,
    )
    fo.close()
    fe.close()
    return p


def _kill(p: subprocess.Popen) -> None:
    """SIGKILL one child and reap it.  SIGKILL on purpose: the abort
    contract is the CRASH contract (fsync-before-claim cursors), and a
    graceful shutdown path would only hide bugs in it."""
    if p.poll() is None:
        try:
            p.send_signal(signal.SIGKILL)
        except OSError:
            pass
    try:
        p.wait(timeout=10)
    except subprocess.TimeoutExpired:  # pragma: no cover — kernel's job
        pass


# -- the scan supervisor -----------------------------------------------------


class ScanSupervisor:
    """Supervise a sharded whole-scan reduction/search to completion
    across peer death and hangs (module docstring).

    ``raw_paths`` is the explicit rectangular ``[band][bank]`` grid
    (every file visible to this machine — the multi-host inventory form
    stays the CLI's job).  ``kind`` is ``"reduce"`` (per-band
    ``.fil``/``.h5``) or ``"search"`` (per-player ``.hits``); ``search``
    carries the DedopplerReducer knobs for the latter.  ``nprocs`` is
    the pod size of the FIRST attempt; ``devices_per_proc`` models the
    per-host chip count (what a surviving host can offer a re-plan).

    ``faults`` maps proc id → a ``BLIT_FAULTS`` spec armed in that
    child's environment on attempt 0 ONLY — the seeded chaos schedule
    (``{"0": "mesh.window:kill:after=2"}``); recovery attempts run
    clean.  ``run()`` returns the drill report (attempts, plan history,
    detection/recovery latencies, per-product results)."""

    def __init__(
        self,
        raw_paths: Sequence[Sequence[str]],
        *,
        out_dir: Optional[str] = None,
        out_paths=None,
        kind: str = "reduce",
        nfft: int,
        ntap: int = 4,
        nint: int = 1,
        stokes: str = "I",
        fqav_by: int = 1,
        window: str = "hamming",
        despike: bool = True,
        dtype: str = "float32",
        window_frames: Optional[int] = None,
        max_frames: Optional[int] = None,
        compression: Optional[str] = None,
        search: Optional[Dict] = None,
        nprocs: int = 1,
        devices_per_proc: Optional[int] = None,
        lease_ttl_s: Optional[float] = None,
        poll_s: Optional[float] = None,
        max_attempts: Optional[int] = None,
        grace_s: Optional[float] = None,
        lease_dir: Optional[str] = None,
        faults: Optional[Dict] = None,
        child_env: Optional[Dict[str, str]] = None,
        timeline: Optional[Timeline] = None,
        config: SiteConfig = DEFAULT,
    ):
        if kind not in ("reduce", "search"):
            raise ValueError(f"unknown scan kind {kind!r}")
        self.grid = [list(row) for row in raw_paths]
        self.nband = len(self.grid)
        self.nbank = len(self.grid[0])
        if any(len(r) != self.nbank for r in self.grid):
            raise ValueError("raw_paths must be rectangular")
        self.kind = kind
        self.knobs = dict(
            nfft=nfft, ntap=ntap, nint=nint, stokes=stokes,
            fqav_by=fqav_by, window=window, despike=despike, dtype=dtype,
            max_frames=max_frames, compression=compression,
        )
        self.search = dict(search or {})
        d = recover_defaults(config)
        self.lease_ttl_s = (d["lease_ttl_s"] if lease_ttl_s is None
                            else float(lease_ttl_s))
        self.poll_s = d["poll_s"] if poll_s is None else float(poll_s)
        self.max_attempts = (d["max_attempts"] if max_attempts is None
                             else int(max_attempts))
        self.grace_s = d["grace_s"] if grace_s is None else float(grace_s)
        self.nprocs = max(1, int(nprocs))
        need = self.nband * self.nbank
        self.devices_per_proc = (devices_per_proc
                                 if devices_per_proc else need)
        self.faults = {int(k): v for k, v in (faults or {}).items()}
        self.child_env = dict(child_env or {})
        self.timeline = timeline if timeline is not None else Timeline()
        self.config = config

        self.wf = self._effective_window_frames(window_frames)
        if out_paths is None:
            if out_dir is None:
                raise ValueError("pass out_dir= or out_paths=")
            os.makedirs(out_dir, exist_ok=True)
            if kind == "search":
                out_paths = [
                    [os.path.join(out_dir, f"band{b}bank{k}.hits")
                     for k in range(self.nbank)]
                    for b in range(self.nband)
                ]
            else:
                ext = "h5" if compression else "fil"
                out_paths = [os.path.join(out_dir, f"band{b}.{ext}")
                             for b in range(self.nband)]
        self.out_paths = out_paths
        if lease_dir is None:
            base = out_dir if out_dir is not None else (
                os.path.dirname(self._flat_out_paths()[0]) or ".")
            # Unique per supervisor run: two supervisors sharing an
            # output directory must never beat, age or clean each
            # other's lease/attempt files.
            lease_dir = _unique_lease_dir(base)
        self.lease_dir = lease_dir
        self._state: Dict = {"kind": kind, "phase": "idle", "attempt": 0,
                             "plan": None}

    # -- planning helpers ---------------------------------------------------
    def _flat_out_paths(self) -> List[str]:
        if self.kind == "search":
            return [p for row in self.out_paths for p in row]
        return list(self.out_paths)

    def _effective_window_frames(self, wf: Optional[int]) -> int:
        """The window granularity every attempt (sharded AND pool
        fallback) must share — dispatch shape is part of the
        byte-identity contract, so it is resolved ONCE, here."""
        from blit.config import default_window_frames, search_defaults

        nint = self.knobs["nint"]
        if wf is None:
            wf = default_window_frames(self.knobs["nfft"])
        wf = max((wf // nint) * nint, nint)
        if self.kind == "search":
            T = self.search.get("window_spectra")
            if not T:
                T = search_defaults(self.config)["window_spectra"]
                self.search["window_spectra"] = T
            unit = T * nint
            wf = max((wf // unit) * unit, unit)
        return wf

    def state(self) -> Dict:
        return dict(self._state)

    # -- execution ----------------------------------------------------------
    def run(self) -> Dict:
        from blit.monitor import publishing

        key = _register(self._state)
        report: Dict = {"kind": self.kind, "attempts": [],
                        "window_frames": self.wf}
        alive = self.nprocs
        pending_detect: Optional[float] = None
        # A PREVIOUS run's attempt files must not bleed into this run's
        # report (result collection is per-attempt below, but stale
        # specs/logs are noise in the triage dir too).
        if os.path.isdir(self.lease_dir):
            for name in os.listdir(self.lease_dir):
                if name.endswith((".result.json", ".spec.json",
                                  ".out", ".err")):
                    try:
                        os.unlink(os.path.join(self.lease_dir, name))
                    except OSError:
                        pass
        try:
            with publishing(self.timeline, config=self.config):
                for attempt in range(self.max_attempts):
                    plan = replan(self.nband, self.nbank,
                                  self.devices_per_proc, alive)
                    self._state.update(attempt=attempt, plan=plan.mode,
                                       nprocs=plan.nprocs,
                                       phase=("recovering" if attempt
                                              else "running"))
                    self.timeline.count("recover.attempts")
                    if attempt:
                        rec = self._windows_recomputed()
                        if rec:
                            self.timeline.count(
                                "recover.windows_recomputed", rec)
                    else:
                        rec = 0
                    entry = {"attempt": attempt, "plan": plan.mode,
                             "nprocs": plan.nprocs,
                             "windows_recomputed": rec}
                    report["attempts"].append(entry)
                    if plan.mode == "pool":
                        if pending_detect is not None:
                            resume_s = time.monotonic() - pending_detect
                            self.timeline.observe("recover.resume_s",
                                                  resume_s)
                            entry["resume_s"] = round(resume_s, 4)
                            pending_detect = None
                        log.warning(
                            "scan re-planned onto the pool fallback "
                            "(%d/%d hosts survive, mesh unformable)",
                            alive, self.nprocs)
                        report["result"] = self._run_pool()
                        entry["ok"] = True
                        break
                    ok, failure, first_beat = self._run_sharded(
                        plan, attempt)
                    if pending_detect is not None and first_beat:
                        resume_s = first_beat - pending_detect
                        self.timeline.observe("recover.resume_s",
                                              resume_s)
                        entry["resume_s"] = round(resume_s, 4)
                        pending_detect = None
                    if ok:
                        entry["ok"] = True
                        report["result"] = self._collect_results(attempt)
                        break
                    entry.update(ok=False, failure=failure)
                    self.timeline.observe("recover.detect_s",
                                          failure["detect_s"])
                    self.timeline.count(
                        "recover.peer_hung" if failure["why"] == "hung"
                        else "recover.peer_lost")
                    self._state["phase"] = "recovering"
                    pending_detect = time.monotonic()
                    alive -= 1
                    log.error(
                        "pod proc %d %s (detected in %.2fs); "
                        "re-planning on %d surviving host(s)",
                        failure["proc"], failure["why"],
                        failure["detect_s"], alive)
                else:
                    self._state["phase"] = "failed"
                    raise RuntimeError(
                        f"scan not recovered within {self.max_attempts} "
                        f"attempts; see {self.lease_dir} child logs")
            self._state["phase"] = "done"
            report["recovered"] = len(report["attempts"]) > 1
            return report
        finally:
            _unregister(key)

    # -- one sharded attempt -----------------------------------------------
    def _child_env(self, plan: ScanPlan, proc: int,
                   attempt: int) -> Dict[str, str]:
        env = dict(os.environ)
        env.update(self.child_env)
        env.pop("BLIT_FAULTS", None)  # only the schedule below arms
        # The rig-simulation leg: on the CPU backend the per-host chip
        # count is a flag, so a re-planned share is honored exactly; on
        # a real TPU pod the topology is the hardware's and this is a
        # no-op (JAX_PLATFORMS unset/tpu).
        if env.get("JAX_PLATFORMS", "").lower() == "cpu":
            env["XLA_FLAGS"] = (
                f"--xla_force_host_platform_device_count="
                f"{plan.devices_per_proc}")
        if attempt == 0 and proc in self.faults:
            env["BLIT_FAULTS"] = self.faults[proc]
        return env

    def _run_sharded(self, plan: ScanPlan, attempt: int
                     ) -> Tuple[bool, Optional[Dict], Optional[float]]:
        os.makedirs(self.lease_dir, exist_ok=True)
        for proc in range(plan.nprocs):  # stale leases confuse aging
            try:
                os.unlink(Lease.path_for(self.lease_dir, proc))
            except OSError:
                pass
        port = _free_port() if plan.nprocs > 1 else ""
        children: Dict[int, subprocess.Popen] = {}
        spec_base = dict(
            kind=self.kind, grid=self.grid, out_paths=self.out_paths,
            mesh_shape=[self.nband, self.nbank],
            window_frames=self.wf, knobs=self.knobs,
            search=self.search, lease_dir=self.lease_dir,
            nprocs=plan.nprocs, port=port,
        )
        t_launch = time.monotonic()
        first_beat: Optional[float] = None
        try:
            for proc in range(plan.nprocs):
                spec = dict(spec_base, proc=proc,
                            result=os.path.join(
                                self.lease_dir,
                                f"a{attempt}p{proc}.result.json"))
                children[proc] = _spawn_child(
                    spec,
                    os.path.join(self.lease_dir,
                                 f"a{attempt}p{proc}.spec.json"),
                    self._child_env(plan, proc, attempt),
                    os.path.join(self.lease_dir, f"a{attempt}p{proc}"),
                )
            watches = {
                proc: _LeaseWatch(self.lease_dir, proc,
                                  self.lease_ttl_s, self.grace_s)
                for proc in range(plan.nprocs)
            }
            done: set = set()
            while True:
                time.sleep(self.poll_s)
                for proc, p in children.items():
                    if proc in done:
                        continue
                    w = watches[proc]
                    w.observe()
                    if w.seen and first_beat is None:
                        first_beat = time.monotonic()
                    rc = p.poll()
                    if rc == 0:
                        done.add(proc)
                        continue
                    if rc is not None:
                        # Dead peer (SIGKILL'd by the drill, OOM, a
                        # crash): its watchdog age bounds how long ago
                        # it could have died.
                        return False, self._fail(
                            children, proc, "died",
                            w.age_s() if w.seen
                            else time.monotonic() - t_launch,
                            rc=rc), first_beat
                    if w.stalled():
                        # Hung peer: alive but silent past the lease —
                        # wedged in a collective (or an injected hang).
                        # Detection latency beyond the TTL is ours.
                        return False, self._fail(
                            children, proc, "hung", w.age_s(),
                        ), first_beat
                    if (not w.seen
                            and time.monotonic() - t_launch
                            > self.grace_s):
                        return False, self._fail(
                            children, proc, "hung",
                            time.monotonic() - t_launch), first_beat
                if len(done) == plan.nprocs:
                    return True, None, first_beat
        finally:
            for p in children.values():
                _kill(p)

    def _fail(self, children: Dict[int, subprocess.Popen], proc: int,
              why: str, detect_s: float, rc: Optional[int] = None
              ) -> Dict:
        """Abort the attempt cleanly: SIGKILL every peer (their
        resumable cursor state is crash-safe by design) and describe
        the failure."""
        from blit.observability import flight_recorder

        for other, p in children.items():
            if other != proc:
                _kill(p)
        _kill(children[proc])
        flight_recorder().dump(
            f"supervised scan peer proc{proc} {why} "
            f"(detected after {detect_s:.2f}s); aborting the attempt "
            f"for degrade-and-resume")
        # A supervised-peer death is an incident (ISSUE 20): snapshot
        # the forensics bundle while the evidence (flight ring, request
        # log, history window) is still warm.
        try:
            from blit.history import maybe_incident

            maybe_incident(
                "recover",
                f"supervised scan peer proc{proc} {why} "
                f"(detected after {detect_s:.2f}s)",
                alert={"t": time.time(), "class": "recover",
                       "proc": proc, "why": why,
                       "detect_s": round(float(detect_s), 4), "rc": rc})
        except Exception:  # noqa: BLE001 — paging must not break recover
            log.warning("recover incident bundle failed", exc_info=True)
        return {"proc": proc, "why": why,
                "detect_s": round(float(detect_s), 4), "rc": rc}

    # -- resume bookkeeping -------------------------------------------------
    def _windows_recomputed(self) -> int:
        """Windows the NEXT attempt will re-run: the gap between each
        product's claimed progress and the pod-wide-agreed (window-
        aligned) restart point — the chaos report's recompute cost."""
        nint = self.knobs["nint"]
        if self.kind == "search":
            from blit.search.dedoppler import SearchCursor

            done = []
            for row in self.out_paths:
                for p in row:
                    cur = SearchCursor.load(p)
                    done.append(cur.windows_done if cur else 0)
            if not done:
                return 0
            unit = self.search["window_spectra"] * nint
            swin = self.wf // unit
            agreed = (min(done) // swin) * swin
            return sum(d - agreed for d in done)
        from blit.pipeline import ReductionCursor

        done = []
        for p in self.out_paths:
            cur = ReductionCursor.load(p)
            done.append(cur.frames_done if cur else 0)
        if not done:
            return 0
        agreed = (min(done) // self.wf) * self.wf
        return sum((d - agreed + self.wf - 1) // self.wf for d in done)

    def _collect_results(self, attempt: int) -> Dict:
        """Fold the SUCCESSFUL attempt's per-process result files (only
        — earlier attempts' files describe aborted work)."""
        out: Dict = {}
        prefix = f"a{attempt}p"
        for name in sorted(os.listdir(self.lease_dir)):
            if name.startswith(prefix) and name.endswith(".result.json"):
                try:
                    with open(os.path.join(self.lease_dir, name)) as f:
                        out.update(json.load(f))
                except (OSError, ValueError):
                    continue
        return out

    # -- the pool fallback --------------------------------------------------
    def _run_pool(self) -> Dict:
        """The PR 2 pool path as the terminal degrade: per-player
        reducers, no mesh, no collectives — products byte-identical to
        the sharded plane at the shared ``window_frames``.  The search
        leg RESUMES each player's SearchCursor from the aborted sharded
        attempt (per-player, no pod agreement needed — there are no
        collectives to keep in lockstep); the reduce leg re-runs whole
        bands (the pool path materializes per-band stitches) and clears
        the stale sharded cursors afterwards."""
        k = self.knobs
        if self.kind == "search":
            from blit.search.dedoppler import DedopplerReducer

            out: Dict = {}
            for b, row in enumerate(self.grid):
                for bank, rp in enumerate(row):
                    red = DedopplerReducer(
                        nfft=k["nfft"], ntap=k["ntap"], nint=k["nint"],
                        window=k["window"], dtype=k["dtype"],
                        chunk_frames=self.wf, timeline=self.timeline,
                        **{kk: vv for kk, vv in self.search.items()
                           if kk in ("window_spectra", "top_k",
                                     "snr_threshold", "max_drift_bins",
                                     "kernel", "interpret")},
                    )
                    hdr = red.search_resumable(rp, self.out_paths[b][bank])
                    out[f"{b},{bank}"] = {
                        "path": self.out_paths[b][bank],
                        "windows": hdr.get("search_windows"),
                        "nhits": hdr.get("search_nhits"),
                    }
            return out
        from blit.parallel.scan import reduce_scan_pool_to_files
        from blit.pipeline import ReductionCursor

        written = reduce_scan_pool_to_files(
            self.grid, out_paths=self.out_paths, nfft=k["nfft"],
            ntap=k["ntap"], nint=k["nint"], stokes=k["stokes"],
            fqav_by=k["fqav_by"], window=k["window"],
            despike=k["despike"], dtype=k["dtype"],
            max_frames=k["max_frames"], window_frames=self.wf,
            compression=k["compression"], timeline=self.timeline,
        )
        for p in self.out_paths:
            # The aborted sharded attempt's cursors are stale now: the
            # pool rewrite replaced the products wholesale.
            try:
                os.unlink(ReductionCursor.path_for(p))
            except OSError:
                pass
        return {str(b): {"path": path, "nsamps": hdr.get("nsamps")}
                for b, (path, hdr) in written.items()}


# -- the stream supervisor ---------------------------------------------------


class StreamSupervisor:
    """Supervise ONE live consumer (``stream_reduce`` /
    ``stream_search``) to completion across crash and wedge: the
    consumer runs as a child with ``resume=True`` and a per-append
    lease heartbeat; a dead (nonzero exit / SIGKILL) or hung (stale
    lease) consumer is killed and restarted against the
    still-recording session, rejoining mid-file through the
    :class:`~blit.stream.cursor.StreamCursor` — same bytes as a
    never-restarted consumer.  ``faults`` arms a ``BLIT_FAULTS`` spec
    in the FIRST attempt's environment (the chaos schedule)."""

    def __init__(self, raw: str, out_path: str, *, kind: str = "reduce",
                 knobs: Optional[Dict] = None,
                 search: Optional[Dict] = None,
                 replay_rate: Optional[float] = None,
                 lateness_s: Optional[float] = None,
                 idle_timeout_s: Optional[float] = None,
                 done_path: Optional[str] = None,
                 source: Optional[Dict] = None,
                 lease_ttl_s: Optional[float] = None,
                 poll_s: Optional[float] = None,
                 max_attempts: Optional[int] = None,
                 grace_s: Optional[float] = None,
                 lease_dir: Optional[str] = None,
                 faults: Optional[str] = None,
                 child_env: Optional[Dict[str, str]] = None,
                 timeline: Optional[Timeline] = None,
                 config: SiteConfig = DEFAULT):
        if kind not in ("reduce", "search"):
            raise ValueError(f"unknown stream kind {kind!r}")
        self.raw = raw
        self.out_path = out_path
        self.kind = kind
        self.knobs = dict(knobs or {})
        self.search = dict(search or {})
        self.replay_rate = replay_rate
        self.lateness_s = lateness_s
        self.idle_timeout_s = idle_timeout_s
        self.done_path = done_path
        # A source SPEC (blit.stream.session.source_from_spec) overrides
        # the raw/replay_rate/tail knobs: the child rebuilds the seat's
        # source — packet capture included — from this dict.
        self.source = dict(source) if source else None
        d = recover_defaults(config)
        self.lease_ttl_s = (d["lease_ttl_s"] if lease_ttl_s is None
                            else float(lease_ttl_s))
        self.poll_s = d["poll_s"] if poll_s is None else float(poll_s)
        self.max_attempts = (d["max_attempts"] if max_attempts is None
                             else int(max_attempts))
        self.grace_s = d["grace_s"] if grace_s is None else float(grace_s)
        self.faults = faults
        self.child_env = dict(child_env or {})
        self.timeline = timeline if timeline is not None else Timeline()
        self.config = config
        self.lease_dir = (lease_dir if lease_dir is not None
                          else _unique_lease_dir(
                              os.path.dirname(out_path) or "."))
        self._state: Dict = {"kind": f"stream-{kind}", "phase": "idle",
                             "attempt": 0}

    def state(self) -> Dict:
        return dict(self._state)

    def run(self) -> Dict:
        from blit.monitor import publishing

        key = _register(self._state)
        report: Dict = {"kind": f"stream-{self.kind}", "attempts": []}
        pending_detect: Optional[float] = None
        try:
            with publishing(self.timeline, config=self.config):
                for attempt in range(self.max_attempts):
                    self._state.update(
                        attempt=attempt,
                        phase="recovering" if attempt else "running")
                    self.timeline.count("recover.attempts")
                    entry: Dict = {"attempt": attempt}
                    report["attempts"].append(entry)
                    ok, failure, first_beat = self._run_attempt(attempt)
                    if pending_detect is not None and first_beat:
                        resume_s = first_beat - pending_detect
                        self.timeline.observe("recover.resume_s",
                                              resume_s)
                        entry["resume_s"] = round(resume_s, 4)
                        pending_detect = None
                    if ok:
                        entry["ok"] = True
                        result = os.path.join(
                            self.lease_dir, f"a{attempt}s.result.json")
                        try:
                            with open(result) as f:
                                report["result"] = json.load(f)
                        except (OSError, ValueError):
                            pass
                        break
                    entry.update(ok=False, failure=failure)
                    self.timeline.observe("recover.detect_s",
                                          failure["detect_s"])
                    self.timeline.count(
                        "recover.consumer_hung"
                        if failure["why"] == "hung"
                        else "recover.consumer_lost")
                    self._state["phase"] = "recovering"
                    pending_detect = time.monotonic()
                    log.error(
                        "live consumer %s (detected in %.2fs); "
                        "rejoining the session", failure["why"],
                        failure["detect_s"])
                else:
                    self._state["phase"] = "failed"
                    raise RuntimeError(
                        f"live consumer not recovered within "
                        f"{self.max_attempts} attempts")
            self._state["phase"] = "done"
            report["recovered"] = len(report["attempts"]) > 1
            return report
        finally:
            _unregister(key)

    def _run_attempt(self, attempt: int
                     ) -> Tuple[bool, Optional[Dict], Optional[float]]:
        os.makedirs(self.lease_dir, exist_ok=True)
        try:
            os.unlink(Lease.path_for(self.lease_dir, 0))
        except OSError:
            pass
        env = dict(os.environ)
        env.update(self.child_env)
        env.pop("BLIT_FAULTS", None)
        if attempt == 0 and self.faults:
            env["BLIT_FAULTS"] = self.faults
        spec = dict(
            kind=f"stream-{self.kind}", raw=self.raw,
            out_path=self.out_path, knobs=self.knobs,
            search=self.search, replay_rate=self.replay_rate,
            lateness_s=self.lateness_s,
            idle_timeout_s=self.idle_timeout_s,
            done_path=self.done_path, source=self.source,
            lease_dir=self.lease_dir,
            proc=0,
            result=os.path.join(self.lease_dir,
                                f"a{attempt}s.result.json"),
        )
        p = _spawn_child(
            spec, os.path.join(self.lease_dir, f"a{attempt}s.spec.json"),
            env, os.path.join(self.lease_dir, f"a{attempt}s"))
        t_launch = time.monotonic()
        first_beat: Optional[float] = None
        w = _LeaseWatch(self.lease_dir, 0, self.lease_ttl_s,
                        self.grace_s)
        try:
            while True:
                time.sleep(self.poll_s)
                w.observe()
                if w.seen and first_beat is None:
                    first_beat = time.monotonic()
                rc = p.poll()
                if rc == 0:
                    return True, None, first_beat
                if rc is not None:
                    return False, {
                        "proc": 0, "why": "died", "rc": rc,
                        "detect_s": round(
                            w.age_s() if w.seen
                            else time.monotonic() - t_launch, 4),
                    }, first_beat
                if w.stalled():
                    _kill(p)
                    return False, {"proc": 0, "why": "hung",
                                   "detect_s": round(w.age_s(), 4),
                                   }, first_beat
                if not w.seen and time.monotonic() - t_launch > self.grace_s:
                    _kill(p)
                    return False, {
                        "proc": 0, "why": "hung",
                        "detect_s": round(
                            time.monotonic() - t_launch, 4),
                    }, first_beat
        finally:
            _kill(p)


# -- the supervised child ----------------------------------------------------


def _child_scan(spec: Dict) -> Dict:
    import jax  # noqa: F401 — the child pays the backend import

    if spec["nprocs"] > 1:
        from blit.parallel.multihost import init_multihost

        init_multihost(
            coordinator_address=f"127.0.0.1:{spec['port']}",
            num_processes=spec["nprocs"],
            process_id=spec["proc"],
            cpu_collectives="gloo",
        )
    from blit.parallel import mesh as M

    nband, nbank = spec["mesh_shape"]
    mesh = M.make_mesh(nband, nbank)
    lease = Lease(spec["lease_dir"], spec["proc"])
    lease.beat(-1)  # bring-up marker: distributed init is done
    k = spec["knobs"]
    common = dict(
        out_paths=spec["out_paths"], nfft=k["nfft"], ntap=k["ntap"],
        nint=k["nint"], dtype=k["dtype"], max_frames=k["max_frames"],
        window_frames=spec["window_frames"], mesh=mesh, resume=True,
        heartbeat=lease.beat,
    )
    if spec["kind"] == "search":
        from blit.parallel.sharded import search_scan_sharded_to_files

        s = spec["search"]
        written = search_scan_sharded_to_files(
            spec["grid"], window=k["window"],
            window_spectra=s.get("window_spectra"),
            top_k=s.get("top_k"), snr_threshold=s.get("snr_threshold"),
            max_drift_bins=s.get("max_drift_bins"),
            kernel=s.get("kernel", "auto"),
            interpret=bool(s.get("interpret", False)),
            **common,
        )
        return {
            f"{b},{bank}": {"path": path,
                            "windows": hdr.get("search_windows")}
            for (b, bank), (path, hdr) in written.items()
        }
    from blit.parallel.sharded import reduce_scan_sharded_to_files

    written = reduce_scan_sharded_to_files(
        spec["grid"], stokes=k["stokes"], fqav_by=k["fqav_by"],
        window=k["window"], despike=k["despike"],
        compression=k["compression"], **common,
    )
    return {str(b): {"path": path, "nsamps": hdr.get("nsamps")}
            for b, (path, hdr) in written.items()}


def _child_stream(spec: Dict) -> Dict:
    from blit.stream import FileTailSource, ReplaySource
    from blit.stream.session import source_from_spec

    lease = Lease(spec["lease_dir"], spec["proc"])
    lease.beat(-1)
    if spec.get("source"):
        src = source_from_spec(spec["source"])
    elif spec.get("replay_rate"):
        src = ReplaySource(spec["raw"], rate=spec["replay_rate"])
    else:
        src = FileTailSource(
            spec["raw"], idle_timeout_s=spec.get("idle_timeout_s"),
            done_path=spec.get("done_path"))
    hb = lease.beat
    k = dict(spec["knobs"])
    if spec["kind"] == "stream-search":
        from blit.stream import stream_search

        hdr = stream_search(
            src, spec["out_path"], resume=True, heartbeat=hb,
            lateness_s=spec.get("lateness_s"), **k, **spec["search"])
        out = {"out": spec["out_path"],
               "windows": hdr.get("search_windows"),
               "nhits": hdr.get("search_nhits"),
               "masked": hdr.get("stream_masked_chunks")}
    else:
        from blit.stream import stream_reduce

        hdr = stream_reduce(
            src, spec["out_path"], resume=True, heartbeat=hb,
            lateness_s=spec.get("lateness_s"), **k)
        out = {"out": spec["out_path"], "nsamps": hdr.get("nsamps"),
               "masked": hdr.get("stream_masked_chunks")}
    if hasattr(src, "packet_report"):
        out["packet"] = src.packet_report()
    return out


def _child_main(spec_path: str) -> int:
    with open(spec_path) as f:
        spec = json.load(f)
    if spec["kind"].startswith("stream"):
        result = _child_stream(spec)
    else:
        result = _child_scan(spec)
    tmp = spec["result"] + ".tmp"
    with open(tmp, "w") as f:
        json.dump(result, f)
    os.replace(tmp, spec["result"])
    print("RECOVER-CHILD-OK", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(_child_main(sys.argv[1]))
