"""Observability: per-stage timing, throughput counters, profiler traces,
structured per-host logging.

SURVEY.md §5: the reference's only observability is three ``@warn`` sites
plus the host name stamped into inventory rows.  blit keeps the host/worker
stamping and adds what a GB/s-class pipeline needs: a stage-timing registry
(cheap, always on), optional JAX profiler traces (TensorBoard/Perfetto),
and log records that carry host/worker context.
"""

from __future__ import annotations

import contextlib
import json
import logging
import socket
import time
from collections import defaultdict
from dataclasses import dataclass, field
from typing import Dict, Iterator, Optional


@dataclass
class StageStats:
    """Accumulated wall time + optional byte counts for one pipeline stage."""

    calls: int = 0
    seconds: float = 0.0
    bytes: int = 0

    @property
    def gbps(self) -> float:
        return self.bytes / self.seconds / 1e9 if self.seconds else 0.0


@dataclass
class Timeline:
    """A registry of named stage timings (one per pipeline/driver)."""

    stages: Dict[str, StageStats] = field(default_factory=lambda: defaultdict(StageStats))

    @contextlib.contextmanager
    def stage(self, name: str, nbytes: int = 0) -> Iterator[None]:
        t0 = time.perf_counter()
        try:
            yield
        finally:
            s = self.stages[name]
            s.calls += 1
            s.seconds += time.perf_counter() - t0
            s.bytes += nbytes

    def report(self) -> Dict[str, Dict]:
        return {
            k: {"calls": v.calls, "seconds": round(v.seconds, 6),
                "bytes": v.bytes, "gbps": round(v.gbps, 3)}
            for k, v in sorted(self.stages.items())
        }

    def log(self, logger: Optional[logging.Logger] = None) -> None:
        (logger or logging.getLogger("blit.timeline")).info(
            "timeline %s", json.dumps(self.report())
        )


@contextlib.contextmanager
def profile_trace(logdir: Optional[str]) -> Iterator[None]:
    """JAX profiler trace around a region (TensorBoard/Perfetto readable).
    ``logdir=None`` is a no-op, so call sites need no conditionals."""
    if logdir is None:
        yield
        return
    import jax

    with jax.profiler.trace(logdir):
        yield


class HostContextFilter(logging.Filter):
    """Injects ``host`` and ``worker`` fields into every record so the
    fan-out logs stay attributable (the reference stamps host into every
    inventory row for the same reason, src/gbtworkerfunctions.jl:74)."""

    def __init__(self, worker: int = 0):
        super().__init__()
        self.host = socket.gethostname()
        self.worker = worker

    def filter(self, record: logging.LogRecord) -> bool:
        record.host = self.host
        record.worker = self.worker
        return True


def configure_logging(level: int = logging.INFO, worker: int = 0) -> None:
    """Structured stderr logging with host/worker context for every blit
    logger.  Idempotent: re-calling replaces the previous blit handler (a
    worker re-configuring with its id must not duplicate output)."""
    root = logging.getLogger("blit")
    for h in list(root.handlers):
        if getattr(h, "_blit_handler", False):
            root.removeHandler(h)
    handler = logging.StreamHandler()
    handler._blit_handler = True
    handler.addFilter(HostContextFilter(worker))
    handler.setFormatter(
        logging.Formatter(
            "%(asctime)s %(levelname)s %(host)s/w%(worker)d %(name)s: %(message)s"
        )
    )
    root.setLevel(level)
    root.addHandler(handler)
    # Our handler owns blit output; don't duplicate through root handlers.
    root.propagate = False
