"""Observability: spans, per-stage timing, latency histograms, fleet
telemetry harvest, a crash/stall flight recorder, profiler traces, and
structured per-host logging.

SURVEY.md §5: the reference's only observability is three ``@warn`` sites
plus the host name stamped into inventory rows.  blit keeps the host/worker
stamping and adds what a GB/s-class serving stack needs (ISSUE 5 tentpole):

- a stage-timing registry (:class:`Timeline` — cheap, always on), now
  **mergeable** across processes so a worker fan-out folds into one fleet
  report (:meth:`Timeline.merge` / :func:`merge_fleet`);
- **spans** (:class:`Span`/:class:`Tracer`): request-scoped traces whose
  context propagates through the worker fan-out (pool dispatch, the agent
  wire) so one driver run parents per-worker child spans, exportable as
  Chrome-trace-event JSON (Perfetto-loadable, complementing the JAX
  profiler traces of :func:`profile_trace`);
- **histograms** (:class:`HistogramStats`): log-bucketed, bounded-memory,
  mergeable latency distributions (p50/p90/p99 + exact max) — the load
  signals averages hide;
- a **flight recorder** (:class:`FlightRecorder`): a fixed-size ring of
  recent span/stage/fault events per process, dumped to JSON when a stall
  watchdog trips, a breaker opens, or an agent dies — rendered by
  ``python -m blit trace-view``;
- optional JAX profiler traces (TensorBoard/Perfetto) and log records that
  carry host/worker context (now also as JSON lines for fleet ingestion).
"""

from __future__ import annotations

import contextlib
import itertools
import json
import logging
import math
import os
import socket
import threading
import time
from collections import defaultdict, deque
from dataclasses import dataclass, field
from typing import Dict, Iterable, Iterator, List, Optional, Tuple

log = logging.getLogger("blit.observability")

_HOSTNAME: Optional[str] = None


# Captured once at import: the process's (epoch, monotonic) clock pair.
# Monotonic readings from different processes are incomparable (each
# starts at an arbitrary origin); shipping this anchor beside every
# spool sample, span batch and flight dump lets a forensics reader
# (blit/history.py incident bundles) project any monotonic-relative
# reading onto shared wall-clock time — and quantifies inter-host skew
# when two anchors disagree about "now" (ISSUE 20 satellite).
_WALL_ANCHOR = {"epoch": round(time.time(), 6),
                "mono": round(time.monotonic(), 6)}


def wall_anchor() -> Dict[str, float]:
    """This process's wall-clock anchor: one ``{"epoch", "mono"}`` pair
    captured at import.  ``epoch - mono`` is the process's monotonic
    origin in wall time; two processes' timelines align by comparing
    origins instead of trusting their skewed starts."""
    return dict(_WALL_ANCHOR)


def hostname() -> str:
    """This process's host name (cached — span creation must stay cheap)."""
    global _HOSTNAME
    if _HOSTNAME is None:
        _HOSTNAME = socket.gethostname()
    return _HOSTNAME


# Worker id stamped into spans/snapshots (0 = the driver process by the
# pool's convention); set by configure_logging(worker=...) at worker startup.
_WORKER = 0


@dataclass
class StageStats:
    """Accumulated wall time + optional byte counts for one pipeline stage."""

    calls: int = 0
    seconds: float = 0.0
    bytes: int = 0
    # Declared byte-free: the stage times something that moves no payload
    # (an async dispatch, a blocking wait).  Every OTHER stage with nonzero
    # seconds must report nonzero bytes — the stage table is only
    # sanity-summable against end-to-end GB/s when no stage silently drops
    # its byte count (VERDICT r5 weak #3), and tests pin that invariant.
    byte_free: bool = False

    @property
    def gbps(self) -> float:
        return self.bytes / self.seconds / 1e9 if self.seconds else 0.0


@dataclass
class GaugeStats:
    """A sampled level (queue depth, wait seconds): last value plus the
    observed envelope.  Unlike :class:`StageStats` a gauge is not a running
    total — re-sampling replaces ``last`` instead of accumulating."""

    last: float = 0.0
    lo: float = 0.0
    hi: float = 0.0
    n: int = 0

    def sample(self, value: float) -> None:
        if self.n == 0:
            self.lo = self.hi = value
        else:
            self.lo = min(self.lo, value)
            self.hi = max(self.hi, value)
        self.last = value
        self.n += 1


# Log-bucketed histogram geometry: bucket i covers (base*2^(i-1), base*2^i]
# with base = 1 µs; 64 buckets span 1 µs .. ~584 000 years, so no latency a
# process can observe falls off the top.
_HIST_BASE = 1e-6
_HIST_NBUCKETS = 64
_LOG2 = math.log(2.0)

# Histogram exemplars (ISSUE 15 tentpole #3): when enabled, every
# histogram retains the most recent trace id per bucket, so a p99 bucket
# that pages an SLO resolves to an actual request's trace instead of an
# anonymous count.  Bounded by construction (one (trace, value, t)
# triple per non-empty bucket, 64 buckets).  BLIT_EXEMPLARS=0 is the
# kill switch (the BLIT_SPANS discipline); SiteConfig.exemplars reaches
# here through blit.config.request_log_defaults + set_exemplars().
_EXEMPLARS = os.environ.get("BLIT_EXEMPLARS", "1").lower() not in (
    "0", "false", "off", "")


def set_exemplars(enabled: bool) -> None:
    """Flip per-bucket trace-id exemplar retention process-wide."""
    global _EXEMPLARS
    _EXEMPLARS = bool(enabled)


def exemplars_enabled() -> bool:
    return _EXEMPLARS


def hist_bucket_edges() -> List[float]:
    """The UPPER edge of every histogram bucket, in order: bucket 0
    holds values <= 1 µs, bucket i (i >= 1) covers
    ``(base·2^(i-1), base·2^i]`` — so edge ``i`` is ``base·2^i``.  The
    Prometheus ``le`` labels of the native exposition
    (:func:`render_prometheus`) and the SLO bad-sample cut
    (:func:`blit.monitor.bad_fraction`) both derive from this one list."""
    return [_HIST_BASE * 2.0 ** i for i in range(_HIST_NBUCKETS)]


class HistogramStats:
    """Log-bucketed value distribution: bounded memory (64 counters),
    mergeable across processes, quantiles good to one bucket (a factor of
    2) — latency must be reported as a distribution, not an average
    (ISSUE 5 tentpole #2).  Exact ``min``/``max``/``sum`` ride along so the
    tail operators page on (``max``) is never a bucket estimate."""

    __slots__ = ("counts", "n", "total", "vmin", "vmax", "exemplars")

    def __init__(self):
        self.counts = [0] * _HIST_NBUCKETS
        self.n = 0
        self.total = 0.0
        self.vmin = 0.0
        self.vmax = 0.0
        # bucket index -> [trace_id, value, epoch seconds] of the most
        # recent exemplar landing there; None until one lands (ISSUE 15).
        self.exemplars: Optional[Dict[int, List]] = None

    def observe(self, value: float, trace_id: Optional[str] = None) -> None:
        v = float(value)
        if v <= _HIST_BASE:
            i = 0
        else:
            i = min(_HIST_NBUCKETS - 1,
                    int(math.ceil(math.log(v / _HIST_BASE) / _LOG2)))
        self.counts[i] += 1
        if self.n == 0:
            self.vmin = self.vmax = v
        else:
            self.vmin = min(self.vmin, v)
            self.vmax = max(self.vmax, v)
        self.n += 1
        self.total += v
        if trace_id is None and _EXEMPLARS:
            # The ambient trace (thread-local read — cheap, and only
            # when a span is actually active): the sample becomes that
            # trace's exemplar in its latency bucket.
            ctx = _TRACER.context()
            if ctx:
                trace_id = ctx["trace"]
        if trace_id:
            ex = self.exemplars
            if ex is None:
                ex = self.exemplars = {}
            ex[i] = [trace_id, v, time.time()]

    def tail_exemplar(self) -> Optional[Dict]:
        """The exemplar of the HIGHEST bucket that has one — the trace
        behind the tail latency an operator is chasing.  Returns
        ``{"bucket", "le", "trace", "value", "t"}`` or None."""
        if not self.exemplars:
            return None
        i = max(self.exemplars)
        trace, v, t = self.exemplars[i]
        return {"bucket": i, "le": _HIST_BASE * 2.0 ** i,
                "trace": trace, "value": v, "t": t}

    def percentile(self, p: float) -> float:
        """Quantile estimate (0.0 when empty): the midpoint of the bucket
        the rank falls in, clamped to the observed [min, max] envelope so
        the extremes are exact."""
        if self.n == 0:
            return 0.0
        # Nearest-rank: the 0-based index of the p-th sample.
        rank = min(self.n - 1, max(0, int(math.ceil(p * self.n)) - 1))
        acc = 0
        for i, c in enumerate(self.counts):
            acc += c
            if c and acc > rank:
                lo = _HIST_BASE * 2.0 ** (i - 1) if i else 0.0
                hi = _HIST_BASE * 2.0 ** i
                return min(max((lo + hi) / 2.0, self.vmin), self.vmax)
        return self.vmax

    def merge(self, other: "HistogramStats") -> "HistogramStats":
        """Fold ``other`` into self (commutative/associative: bucket counts
        and totals sum, the envelope widens)."""
        if other.n:
            if self.n == 0:
                self.vmin, self.vmax = other.vmin, other.vmax
            else:
                self.vmin = min(self.vmin, other.vmin)
                self.vmax = max(self.vmax, other.vmax)
        for i, c in enumerate(other.counts):
            if c:
                self.counts[i] += c
        self.n += other.n
        self.total += other.total
        if other.exemplars:
            # "Most recent per bucket" stays true across the fold: the
            # newer timestamp wins, whichever process observed it.
            ex = self.exemplars
            if ex is None:
                ex = self.exemplars = {}
            for i, rec in other.exemplars.items():
                if i not in ex or rec[2] >= ex[i][2]:
                    ex[i] = list(rec)
        return self

    def reset(self) -> None:
        """Zero IN PLACE, preserving identity (the Timeline.reset rule)."""
        for i in range(_HIST_NBUCKETS):
            self.counts[i] = 0
        self.n = 0
        self.total = 0.0
        self.vmin = self.vmax = 0.0
        self.exemplars = None

    def report(self) -> Dict[str, float]:
        mean = self.total / self.n if self.n else 0.0
        return {"n": self.n, "mean": round(mean, 6),
                "p50": round(self.percentile(0.50), 6),
                "p90": round(self.percentile(0.90), 6),
                "p99": round(self.percentile(0.99), 6),
                "max": round(self.vmax, 6)}

    def state(self) -> Dict:
        """JSON-serializable raw state (the harvest wire format — reports
        round, state doesn't, so fleet merges stay exact)."""
        st = {"counts": list(self.counts), "n": self.n,
              "total": self.total, "vmin": self.vmin, "vmax": self.vmax}
        if self.exemplars:
            # JSON keys are strings; from_state re-ints them.
            st["exemplars"] = {str(i): list(rec)
                               for i, rec in self.exemplars.items()}
        return st

    def since(self, st: Dict) -> "HistogramStats":
        """A NEW histogram holding only the samples observed after ``st``
        (a prior :meth:`state`).  Bucket counts / n / total subtract
        exactly; the [min, max] envelope is not invertible, so the delta
        keeps the cumulative one — quantile bucket midpoints stay
        correct, only the envelope clamp is wider than the true window."""
        h = HistogramStats()
        old = st.get("counts", [])
        for i in range(_HIST_NBUCKETS):
            prev = old[i] if i < len(old) else 0
            h.counts[i] = max(0, self.counts[i] - prev)
        h.n = max(0, self.n - int(st.get("n", 0)))
        h.total = max(0.0, self.total - float(st.get("total", 0.0)))
        h.vmin, h.vmax = self.vmin, self.vmax
        if self.exemplars:
            # Exemplars are "most recent", not a running total: the
            # delta keeps the cumulative ones (a tail sample in this
            # window overwrote its bucket's entry anyway).
            h.exemplars = {i: list(rec)
                           for i, rec in self.exemplars.items()}
        return h

    @classmethod
    def from_state(cls, st: Dict) -> "HistogramStats":
        h = cls()
        counts = list(st.get("counts", []))[:_HIST_NBUCKETS]
        h.counts[: len(counts)] = [int(c) for c in counts]
        h.n = int(st.get("n", 0))
        h.total = float(st.get("total", 0.0))
        h.vmin = float(st.get("vmin", 0.0))
        h.vmax = float(st.get("vmax", 0.0))
        for i, rec in (st.get("exemplars") or {}).items():
            try:
                bucket = int(i)
                trace, v, t = rec
            except (TypeError, ValueError):
                continue
            if h.exemplars is None:
                h.exemplars = {}
            h.exemplars[bucket] = [str(trace), float(v), float(t)]
        return h


@dataclass
class Timeline:
    """A registry of named stage timings (one per pipeline/driver)."""

    stages: Dict[str, StageStats] = field(default_factory=lambda: defaultdict(StageStats))
    gauges: Dict[str, GaugeStats] = field(default_factory=lambda: defaultdict(GaugeStats))
    hists: Dict[str, HistogramStats] = field(
        default_factory=lambda: defaultdict(HistogramStats)
    )

    @contextlib.contextmanager
    def stage(
        self, name: str, nbytes: int = 0, byte_free: bool = False
    ) -> Iterator[None]:
        t0 = time.perf_counter()
        try:
            yield
        finally:
            dt = time.perf_counter() - t0
            s = self.stages[name]
            s.calls += 1
            s.seconds += dt
            s.bytes += nbytes
            if byte_free:
                s.byte_free = True
            _FLIGHT.stage_event(name, dt, nbytes)

    def count(self, name: str, n: int = 1) -> None:
        """Record a byte-free event counter as a stage (``calls`` carries
        the count) — retry/mask/degradation events land here so they show
        up in :meth:`report` and in the per-window :meth:`since` tables
        (ISSUE 2: a degraded run must say so in its report)."""
        s = self.stages[name]
        s.calls += n
        s.byte_free = True
        _FLIGHT.event("count", name, n=n)

    def observe(self, name: str, value: float) -> None:
        """Record one sample into a log-bucketed latency/size histogram
        (bounded memory; p50/p90/p99 + max in :meth:`report`) — chunk
        latency, queue wait, readback lag and retry backoff live here
        instead of on gauges, because their tails are the signal."""
        self.hists[name].observe(value)

    def gauge(self, name: str, value: float) -> None:
        """Sample a level gauge (queue depth, per-job wait seconds — the
        serving layer's load signals, ISSUE 3).  Gauges live beside the
        stage table: levels are point samples, not running totals, so they
        must not pollute the byte-summable stage accounting."""
        self.gauges[name].sample(value)

    def reset(self) -> None:
        """Zero every stage and gauge IN PLACE, preserving object
        identity.  This — not ``stages.clear()`` — is how a rig discards
        warmup passes: ``clear()`` orphans any :class:`StageStats` a
        concurrent thread (an output-plane readback/writer thread, a feed
        producer) or a captured local still holds, so their subsequent
        byte/second updates land in objects the report never sees — the
        failure shape behind BENCH_r05's ``"stream": {"s": 350.3,
        "bytes": 0}`` (ISSUE 4 satellite; tests/test_outplane.py pins the
        rig sequence)."""
        for s in list(self.stages.values()):
            s.calls = 0
            s.seconds = 0.0
            s.bytes = 0
        for g in list(self.gauges.values()):
            g.last = g.lo = g.hi = 0.0
            g.n = 0
        for h in list(self.hists.values()):
            h.reset()

    def overlap_efficiency(self, wall: str = "stream",
                           work: Iterable[str] = ("device", "readback",
                                                  "write")) -> float:
        """Record + return the output plane's overlap gauge
        (``overlap.<wall>``): seconds of per-stage work retired per
        wall-clock second of the ``wall`` stage.

        ≈ 1.0 means the plane ran serialized (the wall clock paid for
        every stage in full — the synchronous-output shape BENCH_r05
        measured); → N means N stages fully hid behind each other.
        *Below* 1.0 the wall stage is dominated by something the work
        stages don't time — usually the host read leg (``ingest``) or
        dispatch gaps.  0.0 when the wall stage never ran.  See
        docs/WORKFLOWS.md "Diagnosing a slow link"."""
        wall_s = self.stages[wall].seconds if wall in self.stages else 0.0
        work_s = sum(
            self.stages[k].seconds for k in work if k in self.stages
        )
        eff = work_s / wall_s if wall_s > 0 else 0.0
        self.gauge(f"overlap.{wall}", eff)
        return eff

    def report(self, include_faults: bool = False) -> Dict[str, Dict]:
        out = {}
        # list(): producer threads (the window feeds) insert stage keys
        # concurrently with consumer-side reporting — never iterate the
        # live dict (CPython raises on resize-mid-iteration).  Torn
        # per-stage reads are acceptable for reporting.
        for k, v in sorted(list(self.stages.items())):
            row = {"calls": v.calls, "seconds": round(v.seconds, 6),
                   "bytes": v.bytes, "gbps": round(v.gbps, 3)}
            if v.byte_free:
                row["byte_free"] = True
            out[k] = row
        if self.gauges:
            out["gauges"] = {
                k: {"last": round(g.last, 6), "lo": round(g.lo, 6),
                    "hi": round(g.hi, 6), "n": g.n}
                for k, g in sorted(list(self.gauges.items()))
            }
        if self.hists:
            out["hists"] = {
                k: h.report() for k, h in sorted(list(self.hists.items()))
            }
        if include_faults:
            # Process-wide failure/recovery totals (blit/faults.py):
            # retry.io / retry.remote / mask.antenna / breaker.trip /
            # fault.<point>.<mode>.  Global (not per-timeline) by design —
            # retries deep inside the I/O layer have no timeline in hand.
            from blit import faults

            c = faults.counters()
            if c:
                out["faults"] = c
        return out

    def snapshot(self) -> Dict[str, tuple]:
        """Cheap point-in-time stage counters, for :meth:`since`
        (safe against concurrent producer-thread stage insertion)."""
        return {k: (v.calls, v.seconds, v.bytes)
                for k, v in list(self.stages.items())}

    def hist_quantiles(self, names: Optional[Iterable[str]] = None
                       ) -> Dict[str, Dict]:
        """p50/p99 (+n, max) per named histogram — the compact tail block
        the bench tables embed beside stage means (ISSUE 8 satellite:
        operators read readback/write/chunk-latency TAILS, an average
        hides the burst that actually stalled the plane).  ``names=None``
        reports every histogram with samples."""
        keys = list(self.hists) if names is None else list(names)
        out = {}
        for k in keys:
            h = self.hists.get(k)
            if h is None or h.n == 0:
                continue
            # One quantile-report implementation: project the compact
            # shape out of HistogramStats.report so rounding/percentile
            # changes there propagate here.
            rep = h.report()
            out[k] = {f: rep[f] for f in ("n", "p50", "p99", "max")}
        return out

    def since(self, snap: Dict[str, tuple]) -> Dict[str, Dict]:
        """Per-stage deltas since a :meth:`snapshot` — the per-window stage
        record the windowed drivers report (seconds/bytes spent in each
        stage by ONE window, not the whole run)."""
        out = {}
        for k, v in list(self.stages.items()):
            c0, s0, b0 = snap.get(k, (0, 0.0, 0))
            if v.calls != c0 or v.bytes != b0 or v.seconds != s0:
                out[k] = {"calls": v.calls - c0,
                          "seconds": round(v.seconds - s0, 6),
                          "bytes": v.bytes - b0}
        return out

    def merge(self, other: "Timeline") -> "Timeline":
        """Fold ``other`` into self — the fleet-harvest fold (ISSUE 5
        tentpole #3).  Stage and histogram merges are commutative and
        associative (sums / bucket sums), so a per-host fold and a flat
        fleet fold give the same totals whatever order workers answered
        in (tests/test_telemetry.py pins this).  Gauges keep the widened
        [lo, hi] envelope and the sample count; ``last`` keeps self's
        unless self never sampled (point samples from different processes
        have no meaningful merged "last")."""
        for k, s in list(other.stages.items()):
            d = self.stages[k]
            d.calls += s.calls
            d.seconds += s.seconds
            d.bytes += s.bytes
            if s.byte_free:
                d.byte_free = True
        for k, g in list(other.gauges.items()):
            d = self.gauges[k]
            if g.n:
                if d.n == 0:
                    d.last, d.lo, d.hi = g.last, g.lo, g.hi
                else:
                    d.lo = min(d.lo, g.lo)
                    d.hi = max(d.hi, g.hi)
                d.n += g.n
        for k, h in list(other.hists.items()):
            self.hists[k].merge(h)
        return self

    def state(self) -> Dict:
        """Full JSON-serializable raw state — the telemetry-harvest wire
        format (:func:`telemetry_snapshot`).  Unlike :meth:`report` nothing
        is rounded, so :meth:`from_state` + :meth:`merge` is exact."""
        return {
            "stages": {
                k: {"calls": v.calls, "seconds": v.seconds,
                    "bytes": v.bytes, "byte_free": v.byte_free}
                for k, v in list(self.stages.items())
            },
            "gauges": {
                k: {"last": g.last, "lo": g.lo, "hi": g.hi, "n": g.n}
                for k, g in list(self.gauges.items())
            },
            "hists": {k: h.state() for k, h in list(self.hists.items())},
        }

    @classmethod
    def from_state(cls, st: Dict) -> "Timeline":
        tl = cls()
        for k, v in (st.get("stages") or {}).items():
            s = tl.stages[k]
            s.calls = int(v.get("calls", 0))
            s.seconds = float(v.get("seconds", 0.0))
            s.bytes = int(v.get("bytes", 0))
            s.byte_free = bool(v.get("byte_free", False))
        for k, v in (st.get("gauges") or {}).items():
            g = tl.gauges[k]
            g.last = float(v.get("last", 0.0))
            g.lo = float(v.get("lo", 0.0))
            g.hi = float(v.get("hi", 0.0))
            g.n = int(v.get("n", 0))
        for k, v in (st.get("hists") or {}).items():
            tl.hists[k] = HistogramStats.from_state(v)
        return tl

    def log(self, logger: Optional[logging.Logger] = None) -> None:
        (logger or logging.getLogger("blit.timeline")).info(
            "timeline %s", json.dumps(self.report())
        )


@contextlib.contextmanager
def profile_trace(logdir: Optional[str]) -> Iterator[None]:
    """JAX profiler trace around a region (TensorBoard/Perfetto readable).
    ``logdir=None`` is a no-op, so call sites need no conditionals."""
    if logdir is None:
        yield
        return
    import jax

    with jax.profiler.trace(logdir):
        yield


# -- spans ------------------------------------------------------------------

_id_counter = itertools.count(1)
# Per-process id prefix: spans harvested from N worker processes must not
# collide in the merged trace.  pid alone recycles; add 2 random bytes.
_ID_PREFIX = f"{os.getpid():x}{os.urandom(2).hex()}"
_ID_PID = os.getpid()


def _new_id() -> str:
    global _ID_PREFIX, _ID_PID
    pid = os.getpid()
    if pid != _ID_PID:
        # Forked child (the process pool backend forks on Linux): the
        # inherited prefix AND counter position would collide span ids
        # across every sibling worker — re-key the prefix per process.
        _ID_PREFIX = f"{pid:x}{os.urandom(2).hex()}"
        _ID_PID = pid
    return f"{_ID_PREFIX}.{next(_id_counter):x}"


class Span:
    """One finished traced operation: name, wall start (epoch seconds),
    duration, host/worker/thread identity, trace linkage (trace id, span
    id, parent span id) and small free-form attrs.  Cheap by design —
    created on context-manager entry, recorded on exit."""

    __slots__ = ("name", "t0", "duration_s", "trace_id", "span_id",
                 "parent_id", "host", "worker", "tid", "attrs")

    def __init__(self, name: str, trace_id: str, span_id: str,
                 parent_id: Optional[str], attrs: Optional[Dict]):
        self.name = name
        self.t0 = time.time()
        self.duration_s = 0.0
        self.trace_id = trace_id
        self.span_id = span_id
        self.parent_id = parent_id
        self.host = hostname()
        self.worker = _WORKER
        self.tid = threading.get_ident() & 0x7FFFFFFF
        self.attrs = attrs

    def as_dict(self) -> Dict:
        d = {"name": self.name, "t0": self.t0,
             "duration_s": self.duration_s, "trace": self.trace_id,
             "span": self.span_id, "parent": self.parent_id,
             "host": self.host, "worker": self.worker, "tid": self.tid}
        if self.attrs:
            d["attrs"] = self.attrs
        return d

    @classmethod
    def from_dict(cls, d: Dict) -> "Span":
        sp = cls(d.get("name", "?"), d.get("trace", ""), d.get("span", ""),
                 d.get("parent"), d.get("attrs") or None)
        sp.t0 = float(d.get("t0", 0.0))
        sp.duration_s = float(d.get("duration_s", 0.0))
        sp.host = d.get("host", sp.host)
        sp.worker = int(d.get("worker", 0))
        sp.tid = int(d.get("tid", 0))
        return sp


class Tracer:
    """Always-on, cheap span recorder with ambient (thread-local) trace
    context.

    A :meth:`span` opened with no ambient context starts a new trace; one
    opened inside another span (same thread) or under :meth:`activate`
    (an adopted cross-thread/cross-process context) becomes its child.
    :meth:`context` exports the current ``{"trace", "span"}`` pair — the
    pool dispatch ships it to workers so their spans parent onto the
    driver's (ISSUE 5 tentpole #1).  Finished spans land in a bounded
    deque (oldest dropped) and in the process flight recorder.

    ``enabled=False`` (or ``BLIT_SPANS=0`` in the environment) turns
    :meth:`span` into a near-free no-op — the ingest-bench A/B lever for
    the ≤1 % overhead acceptance bound."""

    def __init__(self, max_spans: int = 16384, enabled: Optional[bool] = None):
        if enabled is None:
            enabled = os.environ.get("BLIT_SPANS", "1").lower() not in (
                "0", "false", "off", "")
        self.enabled = enabled
        self._spans: deque = deque(maxlen=max_spans)
        # Monotonic count of spans EVER recorded — the cursor behind
        # spans_since(), so interval publishers ship each span once
        # without draining the deque out from under export_chrome.
        # The (append, += 1) pair is guarded: `+= 1` alone is not
        # atomic, and a lost increment would silently drop the tail of
        # a spool batch.
        self._total = 0
        self._span_lock = threading.Lock()
        self._tls = threading.local()

    def _stack(self) -> List:
        st = getattr(self._tls, "stack", None)
        if st is None:
            st = self._tls.stack = []
        return st

    @contextlib.contextmanager
    def span(self, name: str, **attrs) -> Iterator[Optional[Span]]:
        """Time a traced operation.  Yields the live :class:`Span` (or
        ``None`` when tracing is disabled); extra keyword args become
        span attrs."""
        if not self.enabled:
            yield None
            return
        stack = self._stack()
        if stack:
            trace_id, parent_id = stack[-1]
        else:
            trace_id, parent_id = _new_id(), None
        sp = Span(name, trace_id, _new_id(), parent_id, attrs or None)
        stack.append((trace_id, sp.span_id))
        p0 = time.perf_counter()
        try:
            yield sp
        finally:
            sp.duration_s = time.perf_counter() - p0
            stack.pop()
            with self._span_lock:
                self._spans.append(sp)
                self._total += 1
            _FLIGHT.span_event(sp)

    @contextlib.contextmanager
    def activate(self, ctx: Optional[Dict]) -> Iterator[None]:
        """Adopt a ``{"trace", "span"}`` context exported by
        :meth:`context` in another thread or process: spans opened inside
        become children of that remote span."""
        if not ctx or not self.enabled:
            yield
            return
        stack = self._stack()
        stack.append((str(ctx.get("trace", "")), str(ctx.get("span", ""))))
        try:
            yield
        finally:
            stack.pop()

    def context(self) -> Optional[Dict]:
        """The ambient ``{"trace", "span"}`` pair (None outside any span
        or with tracing disabled) — ship it across the fan-out."""
        if not self.enabled:
            return None
        stack = getattr(self._tls, "stack", None)
        if not stack:
            return None
        trace_id, span_id = stack[-1]
        return {"trace": trace_id, "span": span_id}

    def spans(self) -> List[Span]:
        return list(self._spans)

    def span_dicts(self) -> List[Dict]:
        return [s.as_dict() for s in self._spans]

    def spans_since(self, cursor: int) -> Tuple[int, List[Dict]]:
        """Span dicts recorded after a prior cursor → ``(new cursor,
        spans)`` — the interval publisher's batch surface (ISSUE 15
        tentpole #4): each tick ships only the spans finished since the
        last one, so a spool line stays proportional to the interval,
        not the run.  Spans that aged out of the bounded deque between
        slow ticks are lost (by design — the deque bounds memory)."""
        with self._span_lock:
            total = self._total
            new = total - int(cursor)
            if new <= 0:
                return total, []
            recent = list(self._spans)
        if new < len(recent):
            recent = recent[-new:]
        return total, [s.as_dict() for s in recent]

    def ingest(self, span_dicts: Iterable[Dict]) -> None:
        """Adopt foreign spans (a fleet harvest) into this tracer so one
        :meth:`export_chrome` covers driver and workers."""
        for d in span_dicts:
            try:
                sp = Span.from_dict(d)
            except (TypeError, ValueError):  # malformed harvest entry
                continue
            with self._span_lock:
                self._spans.append(sp)
                self._total += 1

    def reset(self) -> None:
        self._spans.clear()

    def export_chrome(self, path: Optional[str] = None,
                      extra: Optional[Iterable[Dict]] = None):
        """Render the recorded spans as Chrome trace events (Perfetto /
        ``chrome://tracing`` loadable).  ``extra`` takes harvested span
        dicts to merge in.  Returns the event document; writes JSON to
        ``path`` when given and returns the path instead."""
        spans = self.spans()
        if extra:
            spans = spans + [Span.from_dict(d) for d in extra]
        # Dedupe by span id: with the in-process pool backends a harvest
        # returns the driver's own spans, so recorded + ``extra`` overlap.
        seen, unique = set(), []
        for sp in spans:
            if sp.span_id in seen:
                continue
            seen.add(sp.span_id)
            unique.append(sp)
        spans = unique
        # Stable pid per (host, worker) so each worker renders as its own
        # process track, named.
        pids: Dict = {}
        events: List[Dict] = []
        for sp in spans:
            key = (sp.host, sp.worker)
            pid = pids.get(key)
            if pid is None:
                pid = pids[key] = len(pids) + 1
                events.append({"ph": "M", "pid": pid, "tid": 0,
                               "name": "process_name",
                               "args": {"name": f"{sp.host}/w{sp.worker}"}})
            ev = {"name": sp.name, "cat": "blit", "ph": "X",
                  "ts": sp.t0 * 1e6, "dur": max(sp.duration_s, 1e-7) * 1e6,
                  "pid": pid, "tid": sp.tid,
                  "args": {"trace": sp.trace_id, "span": sp.span_id,
                           "parent": sp.parent_id}}
            if sp.attrs:
                ev["args"].update(sp.attrs)
            events.append(ev)
        doc = {"traceEvents": events, "displayTimeUnit": "ms"}
        if path is None:
            return doc
        with open(path, "w") as f:
            json.dump(doc, f)
        return path


_TRACER = Tracer()


def tracer() -> Tracer:
    """The process-wide tracer (workers harvest it; drivers export it)."""
    return _TRACER


def span(name: str, **attrs):
    """Module-level convenience: ``with observability.span("leg"): ...``"""
    return _TRACER.span(name, **attrs)


def new_id() -> str:
    """A fresh process-unique id in the span-id format — request ids
    (:class:`RequestLog`) share the spans' id space so a record, a span
    and a log line are all greppable by the same token."""
    return _new_id()


# -- flight recorder --------------------------------------------------------


class FlightRecorder:
    """A fixed-size ring of recent span/stage/fault events, dumped to JSON
    when something trips (ISSUE 5 tentpole #4): a rotation stall watchdog,
    an opened circuit breaker, a dead agent.  Recording must be cheap
    enough to leave on (bounded deque appends, no locks — CPython deque
    appends are atomic); dumping is rate-limited so a retry storm writes
    one incident file, not hundreds.  ``python -m blit trace-view``
    renders a dump into an incident summary."""

    # Bound on distinct rate-limit clocks (ISSUE 15 satellite): reasons
    # carry per-instance detail, so the keyed dict must not grow without
    # bound under adversarial reason churn.
    _MAX_DUMP_KEYS = 64

    def __init__(self, capacity: int = 512, min_interval_s: float = 60.0):
        self._ring: deque = deque(maxlen=capacity)
        self.min_interval_s = min_interval_s
        # Rate limiting is PER REASON CLASS (ISSUE 15 satellite), not
        # one global clock: an SLO-breach dump must not starve a
        # first-of-kind stall dump that lands seconds later.  Keys are
        # the reason's leading "name" segment (before the first ":" or
        # "—"), or an explicit dump(key=...).
        self._last_dump: Dict[str, float] = {}
        self._dump_seq = 0
        self._dump_lock = threading.Lock()

    @staticmethod
    def _reason_key(reason: str) -> str:
        head = reason.split("—", 1)[0].split(":", 1)[0].strip()
        return head[:64] or "dump"

    # -- recording (hot paths) --------------------------------------------
    def event(self, kind: str, name: str, **fields) -> None:
        e = {"t": time.time(), "kind": kind, "name": name}
        if fields:
            e.update(fields)
        self._ring.append(e)

    def span_event(self, sp: Span) -> None:
        self._ring.append({"t": sp.t0, "kind": "span", "name": sp.name,
                           "dur_s": round(sp.duration_s, 6),
                           "span": sp.span_id, "parent": sp.parent_id})

    def stage_event(self, name: str, seconds: float, nbytes: int) -> None:
        self._ring.append({"t": time.time(), "kind": "stage", "name": name,
                           "s": round(seconds, 6), "bytes": nbytes})

    def events(self) -> List[Dict]:
        return list(self._ring)

    def clear(self) -> None:
        self._ring.clear()

    # -- dumping (incident path) ------------------------------------------
    def dump(self, reason: str, path: Optional[str] = None,
             force: bool = False, key: Optional[str] = None) -> Optional[str]:
        """Write the incident JSON (ring + fault counters + process
        timeline + recent spans) and return its path.  Never raises (the
        caller is already mid-incident); returns None when rate-limited
        (``force=True`` overrides) or when ``BLIT_FLIGHT_DISABLE`` is
        set.  The rate limit is per reason CLASS (``key``, default the
        reason's leading name segment) — distinct incident kinds never
        starve each other (ISSUE 15 satellite)."""
        if os.environ.get("BLIT_FLIGHT_DISABLE"):
            return None
        try:
            now = time.monotonic()
            k = key if key is not None else self._reason_key(reason)
            with self._dump_lock:
                last = self._last_dump.get(k, float("-inf"))
                if not force and now - last < self.min_interval_s:
                    return None
                if (k not in self._last_dump
                        and len(self._last_dump) >= self._MAX_DUMP_KEYS):
                    # Evict the stalest clock: new incident kinds keep
                    # their own limiter without unbounded growth.
                    self._last_dump.pop(
                        min(self._last_dump, key=self._last_dump.get))
                self._last_dump[k] = now
            from blit import faults

            doc = {
                "reason": reason,
                "t": time.time(),
                "host": hostname(),
                "pid": os.getpid(),
                "worker": _WORKER,
                "anchor": wall_anchor(),
                "events": self.events(),
                "faults": faults.counters(),
                "timeline": process_timeline().report(),
                "spans": [s.as_dict() for s in _TRACER.spans()[-64:]],
            }
            # Correlate the incident with the request that tripped it
            # (ISSUE 15 satellite): when a span is active on the dumping
            # thread, its trace/span ids land in the dump — a flight
            # record and a stitched fleet trace become greppable by one
            # token.
            ctx = _TRACER.context()
            if ctx:
                doc["trace"] = ctx.get("trace")
                doc["span"] = ctx.get("span")
            if path is None:
                d = os.environ.get("BLIT_FLIGHT_DIR")
                if not d:
                    import tempfile

                    d = tempfile.gettempdir()
                # The per-process sequence number keeps two same-second
                # dumps (now possible: rate limiting is per REASON) from
                # overwriting each other's file.
                with self._dump_lock:
                    self._dump_seq += 1
                    seq = self._dump_seq
                path = os.path.join(
                    d, f"blit-flight-{hostname()}-{os.getpid()}-"
                       f"{int(doc['t'])}-{seq}.json")
            tmp = path + ".tmp"
            with open(tmp, "w") as f:
                json.dump(doc, f)
            os.replace(tmp, path)
            log.error("flight recorder dumped to %s (%s)", path, reason)
            return path
        except Exception:  # noqa: BLE001 — never mask the real incident
            log.warning("flight recorder dump failed", exc_info=True)
            return None


_FLIGHT = FlightRecorder()


def flight_recorder() -> FlightRecorder:
    """The process-wide flight recorder."""
    return _FLIGHT


class StallWatchdog:
    """The one producer-progress stall discipline behind every threaded
    plane (ISSUE 7 satellite: this used to be four near-identical poll
    loops).  A thread that owns real progress calls :meth:`beat`;
    back-pressure waits count as progress (the waiter is the slow side
    there, not the producer).  The poll side sizes its waits with
    :meth:`poll_s` and calls :meth:`check` on every empty poll — when no
    beat landed for ``timeout_s`` while the watched thread is still
    ``active``, the flight recorder dumps the incident trail (BEFORE the
    raise unwinds and teardown noise overwrites the ring) and a
    ``RuntimeError`` bounds the hang.  ``timeout_s=None`` disarms
    (checks are no-ops; polls use their base interval).

    Users: :class:`blit.pipeline.BufferRotation` (ingest producer),
    :class:`blit.outplane.OutputRotation` (readback thread),
    :class:`blit.outplane.AsyncSink` (writer thread, append and flush
    sides), and the streaming chunk feed
    (:class:`blit.stream.LiveRawStream`)."""

    def __init__(self, timeout_s: Optional[float], name: str,
                 what: str = "a wedged producer would otherwise hang"):
        self.timeout_s = timeout_s
        self.name = name
        self.what = what
        self._beat = time.monotonic()

    def beat(self) -> None:
        """Mark producer progress (cheap; called from the owning thread —
        concurrent float stores are atomic in CPython)."""
        self._beat = time.monotonic()

    def age_s(self) -> float:
        """Seconds since the last beat — the raw staleness the supervisor
        planes (blit/recover.py) report as detection latency when a
        watchdog (or its cross-process twin, a heartbeat lease) expires."""
        return time.monotonic() - self._beat

    def poll_s(self, base: float = 0.2) -> float:
        """The poll interval a waiter should use: ``base`` unarmed, else
        clamped so the stall fires within ~half a timeout of reality."""
        if self.timeout_s is None:
            return base
        return min(base, max(0.05, self.timeout_s / 2))

    def stalled(self, active: bool = True) -> bool:
        return (
            self.timeout_s is not None
            and active
            and time.monotonic() - self._beat > self.timeout_s
        )

    def trip(self, detail: str) -> None:
        """Dump the incident and raise (call sites that already know
        they stalled)."""
        msg = (
            f"{self.name}: {detail} — no progress for > "
            f"{self.timeout_s}s (stall watchdog; {self.what})"
        )
        flight_recorder().dump(msg)
        raise RuntimeError(msg)

    def check(self, detail: str, active: bool = True) -> None:
        """Raise via :meth:`trip` iff stalled; no-op otherwise."""
        if self.stalled(active):
            self.trip(detail)


def render_flight_dump(doc: Dict, tail: int = 40) -> str:
    """A flight-recorder dump as a readable incident summary (the
    ``python -m blit trace-view`` body): what tripped, where, the fault
    counters, and the last events before the trip."""
    lines = []
    t = doc.get("t", 0.0)
    when = time.strftime("%Y-%m-%d %H:%M:%S", time.gmtime(t)) if t else "?"
    lines.append("=== blit flight record ===")
    lines.append(f"reason : {doc.get('reason', '?')}")
    lines.append(f"where  : {doc.get('host', '?')}/w{doc.get('worker', 0)} "
                 f"pid {doc.get('pid', '?')}")
    lines.append(f"when   : {when} UTC")
    anchor = doc.get("anchor") or {}
    if anchor:
        # epoch - mono = the dumping process's monotonic origin on the
        # wall clock — what cross-process bundle timelines align on.
        origin = anchor.get("epoch", 0.0) - anchor.get("mono", 0.0)
        lines.append(f"anchor : epoch={anchor.get('epoch')} "
                     f"mono={anchor.get('mono')} "
                     f"(mono origin {origin:.3f})")
    if doc.get("trace"):
        # The ambient trace at dump time (ISSUE 15): follow it into the
        # stitched fleet trace (`blit trace-view --fleet ... --trace`).
        lines.append(f"trace  : {doc['trace']} "
                     f"(span {doc.get('span', '?')})")
    faults_c = doc.get("faults") or {}
    if faults_c:
        lines.append("fault counters:")
        for k, v in sorted(faults_c.items()):
            lines.append(f"  {k:<32} {v}")
    tl = doc.get("timeline") or {}
    stages = {k: v for k, v in tl.items()
              if isinstance(v, dict) and "calls" in v}
    if stages:
        lines.append("process timeline (stages):")
        for k, v in sorted(stages.items()):
            lines.append(
                f"  {k:<20} calls={v.get('calls', 0):<8} "
                f"s={v.get('seconds', 0.0):<12} bytes={v.get('bytes', 0)}")
    events = doc.get("events") or []
    lines.append(f"last {min(tail, len(events))} of {len(events)} recorded "
                 "events (oldest first):")
    for e in events[-tail:]:
        ts = time.strftime("%H:%M:%S", time.gmtime(e.get("t", 0.0)))
        kind = e.get("kind", "?")
        name = e.get("name", "?")
        rest = {k: v for k, v in e.items()
                if k not in ("t", "kind", "name")}
        detail = " ".join(f"{k}={v}" for k, v in rest.items())
        lines.append(f"  {ts} [{kind:<5}] {name} {detail}".rstrip())
    return "\n".join(lines)


# -- per-request access records (ISSUE 15 tentpole #2) -----------------------


class RequestLog:
    """A bounded JSON-lines log of per-request access records — the
    serving planes' flight-data recorder for REQUESTS: one line per
    request with request/trace id, fingerprint, client, priority,
    deadline remaining, tier outcome, queue wait, routed peer, hedge
    outcome, bytes and status (`python -m blit requests` tails,
    filters and aggregates a spool of these).

    Bounded by SIZE ROTATION: when the live file passes ``max_bytes``
    it rotates to ``<path>.1`` .. ``<path>.<max_files-1>`` and the
    oldest rolls off — a busy front door's log occupies
    ``max_bytes * max_files`` at most, forever.  Appends are one
    ``json.dumps`` + write under a lock; :meth:`record` never raises
    (access logging must not fail a request)."""

    def __init__(self, path: str, *, max_bytes: int = 8 << 20,
                 max_files: int = 4):
        self.path = path
        self.max_bytes = max(4096, int(max_bytes))
        self.max_files = max(1, int(max_files))
        self._lock = threading.Lock()
        self._f = None
        self._size = 0

    def _open(self) -> None:
        d = os.path.dirname(self.path)
        if d:
            os.makedirs(d, exist_ok=True)
        self._f = open(self.path, "a")
        self._size = self._f.tell()

    def _rotate_locked(self) -> None:
        self._f.close()
        self._f = None
        if self.max_files == 1:
            os.remove(self.path)  # a one-file budget truncates in place
        else:
            for i in range(self.max_files - 1, 0, -1):
                src = self.path if i == 1 else f"{self.path}.{i - 1}"
                dst = f"{self.path}.{i}"
                if os.path.exists(src):
                    os.replace(src, dst)
        self._open()

    def record(self, **fields) -> None:
        """Append one access record (a ``t`` timestamp is stamped in;
        None-valued fields are dropped so lines stay compact)."""
        try:
            doc = {"t": round(time.time(), 6)}
            doc.update({k: v for k, v in fields.items() if v is not None})
            line = json.dumps(doc) + "\n"
            with self._lock:
                if self._f is None:
                    self._open()
                self._f.write(line)
                self._f.flush()
                self._size += len(line)
                if self._size >= self.max_bytes:
                    self._rotate_locked()
        except Exception:  # noqa: BLE001 — logging must not fail requests
            log.warning("request log append failed", exc_info=True)

    def files(self) -> List[str]:
        """Every rotation member that exists, oldest first."""
        out = [f"{self.path}.{i}"
               for i in range(self.max_files - 1, 0, -1)
               if os.path.exists(f"{self.path}.{i}")]
        if os.path.exists(self.path):
            out.append(self.path)
        return out

    def close(self) -> None:
        with self._lock:
            if self._f is not None:
                with contextlib.suppress(OSError):
                    self._f.close()
                self._f = None


def request_log_for(role: str, config=None) -> Optional[RequestLog]:
    """The configured :class:`RequestLog` for a serving component
    (``role`` names it in the spool: ``requests-<role>-<host>-<pid>``),
    or None when request logging is disabled — the disabled path is one
    dict lookup per request (:func:`blit.config.request_log_defaults`:
    ``BLIT_REQUEST_LOG`` / ``SiteConfig.request_log_dir``).

    Also applies the config's ``exemplars`` knob (process-wide — every
    serving component constructs through here, so a peer/service-only
    process honors ``SiteConfig.exemplars=False`` exactly like a door;
    last constructor wins when configs disagree in one process)."""
    from blit.config import DEFAULT, request_log_defaults

    d = request_log_defaults(DEFAULT if config is None else config)
    set_exemplars(d["exemplars"])
    if not d["dir"]:
        return None
    path = os.path.join(
        d["dir"], f"requests-{role}-{hostname()}-{os.getpid()}.jsonl")
    return RequestLog(path, max_bytes=d["max_bytes"],
                      max_files=d["files"])


# -- fleet trace stitching (ISSUE 15 tentpole #4) ----------------------------


def span_process(span_id: str) -> str:
    """The process prefix of a span/trace id (everything before the
    counter): ids are minted as ``<pid-hex + 2 random bytes>.<n>``, so
    two spans share a prefix iff one process recorded them."""
    return str(span_id).split(".", 1)[0]


def cross_process_pairs(span_dicts: Iterable[Dict]) -> int:
    """How many parent→child span edges CROSS a process boundary — the
    stitched-trace acceptance metric (ISSUE 15): a fleet request whose
    peer-side spans parent onto the front-door span contributes at
    least one."""
    spans = list(span_dicts)
    by_id = {s.get("span"): s for s in spans if s.get("span")}
    pairs = 0
    for s in spans:
        parent = s.get("parent")
        if not parent or parent not in by_id:
            continue
        if span_process(parent) != span_process(s.get("span", "")):
            pairs += 1
    return pairs


def trace_summary(span_dicts: Iterable[Dict]) -> Dict:
    """Shape of a stitched span set: totals, distinct traces/processes,
    and the cross-process edge count."""
    spans = list(span_dicts)
    traces = {s.get("trace") for s in spans if s.get("trace")}
    procs = {span_process(s.get("span", "")) for s in spans
             if s.get("span")}
    return {"spans": len(spans), "traces": len(traces),
            "processes": len(procs),
            "cross_process_pairs": cross_process_pairs(spans)}


def render_trace_tree(span_dicts: Iterable[Dict], trace_id: str,
                      max_spans: int = 200) -> str:
    """One trace as an indented parent→child tree (the ``blit
    trace-view --fleet --trace`` body): every span's name, duration,
    host/process and hedge tag, children under parents, orphans (their
    parent aged out of a bounded buffer) at the root."""
    spans = [s for s in span_dicts if s.get("trace") == trace_id]
    spans.sort(key=lambda s: s.get("t0", 0.0))
    spans = spans[:max_spans]
    ids = {s.get("span") for s in spans}
    children: Dict[str, List[Dict]] = {}
    roots: List[Dict] = []
    for s in spans:
        parent = s.get("parent")
        if parent and parent in ids:
            children.setdefault(parent, []).append(s)
        else:
            roots.append(s)
    lines = [f"trace {trace_id}: {len(spans)} span(s)"]

    def walk(s: Dict, depth: int) -> None:
        attrs = s.get("attrs") or {}
        tag = " hedge=1" if attrs.get("hedge") else ""
        where = f"{s.get('host', '?')}/{span_process(s.get('span', ''))}"
        lines.append(
            f"  {'  ' * depth}{s.get('name', '?'):<24} "
            f"{s.get('duration_s', 0.0) * 1e3:9.3f} ms  [{where}]{tag}")
        for c in children.get(s.get("span"), []):
            walk(c, depth + 1)

    for r in roots:
        walk(r, 0)
    return "\n".join(lines)


# -- process telemetry / fleet harvest --------------------------------------

_PROCESS_TL = Timeline()


def process_timeline() -> Timeline:
    """The process-wide ambient :class:`Timeline` — what worker-side entry
    points (``blit.workers.reduce_raw``, retry backoff, ...) record on so
    :func:`telemetry_snapshot` has one table to ship when the driver
    harvests the fleet."""
    return _PROCESS_TL


def telemetry_snapshot(reset: bool = False, spans: bool = True) -> Dict:
    """This process's telemetry, JSON/pickle-safe (plain builtins only —
    it crosses the agent wire): host/pid/worker identity, the process
    timeline's raw state, the fault counters, and the finished spans.
    The harvest endpoint ``WorkerPool.harvest_telemetry`` broadcasts.

    ``reset=True`` zeroes the process timeline (identity-preserving) and
    drains the span buffer after snapshotting — interval-scrape mode."""
    from blit import faults

    out = {
        "host": hostname(),
        "pid": os.getpid(),
        "worker": _WORKER,
        "anchor": wall_anchor(),
        "timeline": _PROCESS_TL.state(),
        "faults": faults.counters(),
        "spans": _TRACER.span_dicts() if spans else [],
    }
    if reset:
        _PROCESS_TL.reset()
        _TRACER.reset()
    return out


def merge_fleet(snapshots: Iterable[Optional[Dict]],
                errors: Optional[Dict[str, str]] = None) -> Dict:
    """Fold :func:`telemetry_snapshot` results into ONE per-host-keyed
    fleet report (ISSUE 5 tentpole #3): every host gets its merged stage
    table and fault counters, and the ``fleet`` entry is the whole-run
    fold.  Snapshots from the same (host, pid) are counted once — with
    the thread/local backends every "worker" answers from the driver
    process, and double-merging would inflate every counter."""
    hosts: Dict[str, Dict] = {}
    fleet = Timeline()
    fleet_faults: Dict[str, int] = {}
    spans: List[Dict] = []
    # One snapshot per (host, pid), keeping the RICHEST: with the
    # thread/local backends every "worker" answers from one process, and
    # under reset=True whichever call ran first drained the telemetry —
    # the later calls return empty snapshots that must not shadow the
    # populated one (first-wins would nondeterministically drop the run).
    best: Dict = {}
    for snap in snapshots:
        if not isinstance(snap, dict) or "host" not in snap:
            continue
        key = (snap["host"], snap.get("pid"))
        richness = (len((snap.get("timeline") or {}).get("stages") or {})
                    + len(snap.get("spans") or []))
        if key not in best or richness > best[key][0]:
            best[key] = (richness, snap)
    for _, snap in best.values():
        entry = hosts.setdefault(
            snap["host"], {"workers": [], "tl": Timeline(), "faults": {}})
        entry["workers"].append(
            {"pid": snap.get("pid"), "worker": snap.get("worker", 0)})
        tl = Timeline.from_state(snap.get("timeline") or {})
        entry["tl"].merge(tl)
        fleet.merge(tl)
        for k, v in (snap.get("faults") or {}).items():
            entry["faults"][k] = entry["faults"].get(k, 0) + v
            fleet_faults[k] = fleet_faults.get(k, 0) + v
        spans.extend(snap.get("spans") or [])
    report = {
        "hosts": {
            h: {"workers": e["workers"], "stages": e["tl"].report(),
                # Raw (unrounded) bucket counts per histogram: what the
                # native Prometheus histogram series render from
                # (ISSUE 11 satellite) — the quantile block in "stages"
                # is a rounded projection, not mergeable or bucketable.
                "hist_state": {k: hh.state()
                               for k, hh in list(e["tl"].hists.items())},
                "faults": e["faults"]}
            for h, e in sorted(hosts.items())
        },
        "fleet": fleet.report(),
        "faults": fleet_faults,
        "spans": spans,
    }
    if errors:
        report["errors"] = dict(errors)
    return report


def local_fleet_report() -> Dict:
    """The degenerate single-process fleet report (driver only) — what a
    run with no pool, or the tier-1 CI job, publishes."""
    return merge_fleet([telemetry_snapshot()])


def maybe_write_report(path: Optional[str] = None) -> Optional[str]:
    """Write :func:`local_fleet_report` JSON to ``path`` (default: the
    ``BLIT_TELEMETRY_OUT`` environment variable; no-op when unset).  The
    CI artifact hook — never raises."""
    path = path or os.environ.get("BLIT_TELEMETRY_OUT")
    if not path:
        return None
    try:
        with open(path, "w") as f:
            json.dump(local_fleet_report(), f)
        return path
    except Exception:  # noqa: BLE001 — reporting must not fail the run
        log.warning("telemetry report write to %s failed", path,
                    exc_info=True)
        return None


def prom_escape(value) -> str:
    """Prometheus label-VALUE escaping (exposition format: backslash,
    double quote and newline are the three escapes)."""
    return (str(value).replace("\\", "\\\\").replace("\n", "\\n")
            .replace('"', '\\"'))


# The two exposition content types a /metrics endpoint can answer with:
# exemplars are only legal in the OpenMetrics format, so the servers
# negotiate via the Accept header (the prometheus_client discipline) —
# a legacy text-format scrape must never see an exemplar suffix its
# parser would reject.
PROM_CTYPE = "text/plain; version=0.0.4"
OPENMETRICS_CTYPE = "application/openmetrics-text; version=1.0.0; charset=utf-8"


def wants_openmetrics(accept: Optional[str]) -> bool:
    """Did the scraper negotiate OpenMetrics (exemplar-capable)?"""
    return bool(accept) and "application/openmetrics-text" in accept


def render_prometheus(report: Dict, *, openmetrics: bool = False) -> str:
    """A fleet report (:func:`merge_fleet`) in Prometheus exposition
    format — one scrape body with host-labelled stage/gauge/histogram/
    fault series (the ``python -m blit telemetry --format prom`` output
    and the monitor endpoint's ``/metrics`` body, blit/monitor.py).

    Histograms are NATIVE Prometheus histogram series (ISSUE 11
    satellite): cumulative ``_bucket`` counts at the log2 bucket edges
    (:func:`hist_bucket_edges`) plus exact ``_sum``/``_count``, rendered
    from the per-host raw ``hist_state`` a :func:`merge_fleet` report
    carries — so a real Prometheus server computes any quantile over any
    window, instead of scraping our precomputed p50/p90/p99 (which still
    ride along as ``blit_latency_quantile`` gauges, and are all a saved
    legacy report without raw state can offer).

    ``openmetrics=True`` (the Accept-negotiated mode, ISSUE 15) adds
    per-bucket trace-id EXEMPLARS in OpenMetrics exemplar syntax and the
    ``# EOF`` trailer; the default text format stays exemplar-free —
    the legacy Prometheus text parser rejects the suffix."""
    lines: List[str] = []

    def head(metric: str, mtype: str, help_: str) -> None:
        lines.append(f"# HELP {metric} {help_}")
        lines.append(f"# TYPE {metric} {mtype}")

    head("blit_stage_seconds_total", "counter",
         "Accumulated wall seconds per pipeline stage")
    head("blit_stage_calls_total", "counter", "Stage invocations")
    head("blit_stage_bytes_total", "counter", "Bytes moved per stage")
    head("blit_gauge", "gauge", "Last sampled level")
    head("blit_latency_seconds", "histogram",
         "Log-bucketed latency distribution (64 log2 buckets from 1 us)")
    head("blit_latency_quantile", "gauge",
         "Precomputed latency quantiles (seconds; bucket-midpoint "
         "estimates)")
    head("blit_fault_total", "counter", "Failure/recovery counters")
    edges = hist_bucket_edges()
    for host, e in (report.get("hosts") or {}).items():
        hl = prom_escape(host)
        stages = e.get("stages") or {}
        for k, row in stages.items():
            if k in ("gauges", "hists", "faults") or not isinstance(row, dict):
                continue
            lab = f'{{host="{hl}",stage="{prom_escape(k)}"}}'
            lines.append(f"blit_stage_seconds_total{lab} {row.get('seconds', 0)}")
            lines.append(f"blit_stage_calls_total{lab} {row.get('calls', 0)}")
            lines.append(f"blit_stage_bytes_total{lab} {row.get('bytes', 0)}")
        for k, g in (stages.get("gauges") or {}).items():
            lines.append(
                f'blit_gauge{{host="{hl}",name="{prom_escape(k)}"}} '
                f'{g.get("last", 0)}')
        hist_state = e.get("hist_state") or {}
        for k, h in (stages.get("hists") or {}).items():
            nl = prom_escape(k)
            st = hist_state.get(k)
            if st:
                exemplars = st.get("exemplars") or {}
                acc = 0
                for i, c in enumerate(st.get("counts") or []):
                    if not c:
                        continue
                    acc += int(c)
                    line = (
                        f'blit_latency_seconds_bucket{{host="{hl}",'
                        f'name="{nl}",le="{edges[i]:.10g}"}} {acc}')
                    ex = (exemplars.get(str(i)) or exemplars.get(i)
                          if openmetrics else None)
                    if ex:
                        # OpenMetrics exemplar syntax (ISSUE 15): the
                        # most recent trace id that landed in this
                        # bucket, so a dashboard's tail bucket links
                        # straight to a stitched trace.
                        trace, v, t = ex
                        line += (f' # {{trace_id="{prom_escape(trace)}"}}'
                                 f' {float(v):.9g} {float(t):.3f}')
                    lines.append(line)
                lines.append(
                    f'blit_latency_seconds_bucket{{host="{hl}",'
                    f'name="{nl}",le="+Inf"}} {int(st.get("n", 0))}')
                lines.append(
                    f'blit_latency_seconds_sum{{host="{hl}",name="{nl}"}} '
                    f'{st.get("total", 0.0)}')
                lines.append(
                    f'blit_latency_seconds_count{{host="{hl}",'
                    f'name="{nl}"}} {int(st.get("n", 0))}')
            for q, key in (("0.5", "p50"), ("0.9", "p90"), ("0.99", "p99")):
                lines.append(
                    f'blit_latency_quantile{{host="{hl}",name="{nl}",'
                    f'quantile="{q}"}} {h.get(key, 0)}')
        for k, v in (e.get("faults") or {}).items():
            lines.append(
                f'blit_fault_total{{host="{hl}",'
                f'counter="{prom_escape(k)}"}} {v}')
    if openmetrics:
        lines.append("# EOF")
    return "\n".join(lines) + "\n"


def render_fleet_text(report: Dict) -> str:
    """A fleet report as a human-readable per-host summary (the default
    ``python -m blit telemetry`` output)."""
    lines: List[str] = []
    for host, e in (report.get("hosts") or {}).items():
        workers = e.get("workers") or []
        lines.append(f"host {host} ({len(workers)} worker"
                     f"{'s' if len(workers) != 1 else ''})")
        stages = e.get("stages") or {}
        rows = [(k, v) for k, v in stages.items()
                if isinstance(v, dict) and "calls" in v]
        if rows:
            lines.append(f"  {'stage':<22} {'calls':>8} {'seconds':>12} "
                         f"{'bytes':>16} {'GB/s':>8}")
            for k, v in sorted(rows):
                lines.append(
                    f"  {k:<22} {v.get('calls', 0):>8} "
                    f"{v.get('seconds', 0.0):>12} {v.get('bytes', 0):>16} "
                    f"{v.get('gbps', 0.0):>8}")
        for k, h in sorted((stages.get("hists") or {}).items()):
            lines.append(
                f"  hist {k:<18} n={h.get('n', 0):<7} "
                f"p50={h.get('p50', 0)} p99={h.get('p99', 0)} "
                f"max={h.get('max', 0)}")
        for k, v in sorted((e.get("faults") or {}).items()):
            lines.append(f"  fault {k:<20} {v}")
    errs = report.get("errors") or {}
    for host, msg in sorted(errs.items()):
        lines.append(f"host {host}: HARVEST FAILED — {msg}")
    fleet = report.get("fleet") or {}
    nstages = sum(1 for v in fleet.values()
                  if isinstance(v, dict) and "calls" in v)
    lines.append(f"fleet: {len(report.get('hosts') or {})} hosts, "
                 f"{nstages} stages, "
                 f"{len(report.get('spans') or [])} spans")
    return "\n".join(lines)


class HostContextFilter(logging.Filter):
    """Injects ``host`` and ``worker`` fields into every record so the
    fan-out logs stay attributable (the reference stamps host into every
    inventory row for the same reason, src/gbtworkerfunctions.jl:74)."""

    def __init__(self, worker: int = 0):
        super().__init__()
        self.host = socket.gethostname()
        self.worker = worker

    def filter(self, record: logging.LogRecord) -> bool:
        record.host = self.host
        record.worker = self.worker
        return True


class JsonLineFormatter(logging.Formatter):
    """One JSON object per record (ts/level/host/worker/name/msg) so fleet
    logs are machine-parseable (ISSUE 5 satellite) — a harvest pipeline
    must never re-parse the human format's free text."""

    def format(self, record: logging.LogRecord) -> str:
        doc = {
            "ts": round(record.created, 3),
            "level": record.levelname,
            "host": getattr(record, "host", hostname()),
            "worker": getattr(record, "worker", 0),
            "name": record.name,
            "msg": record.getMessage(),
        }
        if record.exc_info:
            doc["exc"] = self.formatException(record.exc_info)
        return json.dumps(doc)


def configure_logging(level: int = logging.INFO, worker: int = 0,
                      json_lines: bool = False, stream=None) -> None:
    """Structured stderr logging with host/worker context for every blit
    logger.  Idempotent: re-calling replaces the previous blit handler (a
    worker re-configuring with its id must not duplicate output).

    ``json_lines=True`` emits one JSON object per record
    (:class:`JsonLineFormatter`) instead of the human format — worker
    startup threads it via ``BLIT_LOG_JSON`` in the agent environment
    (:mod:`blit.agent`).  ``stream`` overrides the handler target
    (tests capture it); default stderr."""
    global _WORKER
    _WORKER = worker  # stamp spans/snapshots with the same identity
    root = logging.getLogger("blit")
    for h in list(root.handlers):
        if getattr(h, "_blit_handler", False):
            root.removeHandler(h)
    handler = logging.StreamHandler(stream)
    handler._blit_handler = True
    handler.addFilter(HostContextFilter(worker))
    if json_lines:
        handler.setFormatter(JsonLineFormatter())
    else:
        handler.setFormatter(
            logging.Formatter(
                "%(asctime)s %(levelname)s %(host)s/w%(worker)d %(name)s: %(message)s"
            )
        )
    root.setLevel(level)
    root.addHandler(handler)
    # Our handler owns blit output; don't duplicate through root handlers.
    root.propagate = False
