"""Observability: per-stage timing, throughput counters, profiler traces,
structured per-host logging.

SURVEY.md §5: the reference's only observability is three ``@warn`` sites
plus the host name stamped into inventory rows.  blit keeps the host/worker
stamping and adds what a GB/s-class pipeline needs: a stage-timing registry
(cheap, always on), optional JAX profiler traces (TensorBoard/Perfetto),
and log records that carry host/worker context.
"""

from __future__ import annotations

import contextlib
import json
import logging
import socket
import time
from collections import defaultdict
from dataclasses import dataclass, field
from typing import Dict, Iterable, Iterator, Optional


@dataclass
class StageStats:
    """Accumulated wall time + optional byte counts for one pipeline stage."""

    calls: int = 0
    seconds: float = 0.0
    bytes: int = 0
    # Declared byte-free: the stage times something that moves no payload
    # (an async dispatch, a blocking wait).  Every OTHER stage with nonzero
    # seconds must report nonzero bytes — the stage table is only
    # sanity-summable against end-to-end GB/s when no stage silently drops
    # its byte count (VERDICT r5 weak #3), and tests pin that invariant.
    byte_free: bool = False

    @property
    def gbps(self) -> float:
        return self.bytes / self.seconds / 1e9 if self.seconds else 0.0


@dataclass
class GaugeStats:
    """A sampled level (queue depth, wait seconds): last value plus the
    observed envelope.  Unlike :class:`StageStats` a gauge is not a running
    total — re-sampling replaces ``last`` instead of accumulating."""

    last: float = 0.0
    lo: float = 0.0
    hi: float = 0.0
    n: int = 0

    def sample(self, value: float) -> None:
        if self.n == 0:
            self.lo = self.hi = value
        else:
            self.lo = min(self.lo, value)
            self.hi = max(self.hi, value)
        self.last = value
        self.n += 1


@dataclass
class Timeline:
    """A registry of named stage timings (one per pipeline/driver)."""

    stages: Dict[str, StageStats] = field(default_factory=lambda: defaultdict(StageStats))
    gauges: Dict[str, GaugeStats] = field(default_factory=lambda: defaultdict(GaugeStats))

    @contextlib.contextmanager
    def stage(
        self, name: str, nbytes: int = 0, byte_free: bool = False
    ) -> Iterator[None]:
        t0 = time.perf_counter()
        try:
            yield
        finally:
            s = self.stages[name]
            s.calls += 1
            s.seconds += time.perf_counter() - t0
            s.bytes += nbytes
            if byte_free:
                s.byte_free = True

    def count(self, name: str, n: int = 1) -> None:
        """Record a byte-free event counter as a stage (``calls`` carries
        the count) — retry/mask/degradation events land here so they show
        up in :meth:`report` and in the per-window :meth:`since` tables
        (ISSUE 2: a degraded run must say so in its report)."""
        s = self.stages[name]
        s.calls += n
        s.byte_free = True

    def gauge(self, name: str, value: float) -> None:
        """Sample a level gauge (queue depth, per-job wait seconds — the
        serving layer's load signals, ISSUE 3).  Gauges live beside the
        stage table: levels are point samples, not running totals, so they
        must not pollute the byte-summable stage accounting."""
        self.gauges[name].sample(value)

    def reset(self) -> None:
        """Zero every stage and gauge IN PLACE, preserving object
        identity.  This — not ``stages.clear()`` — is how a rig discards
        warmup passes: ``clear()`` orphans any :class:`StageStats` a
        concurrent thread (an output-plane readback/writer thread, a feed
        producer) or a captured local still holds, so their subsequent
        byte/second updates land in objects the report never sees — the
        failure shape behind BENCH_r05's ``"stream": {"s": 350.3,
        "bytes": 0}`` (ISSUE 4 satellite; tests/test_outplane.py pins the
        rig sequence)."""
        for s in list(self.stages.values()):
            s.calls = 0
            s.seconds = 0.0
            s.bytes = 0
        for g in list(self.gauges.values()):
            g.last = g.lo = g.hi = 0.0
            g.n = 0

    def overlap_efficiency(self, wall: str = "stream",
                           work: Iterable[str] = ("device", "readback",
                                                  "write")) -> float:
        """Record + return the output plane's overlap gauge
        (``overlap.<wall>``): seconds of per-stage work retired per
        wall-clock second of the ``wall`` stage.

        ≈ 1.0 means the plane ran serialized (the wall clock paid for
        every stage in full — the synchronous-output shape BENCH_r05
        measured); → N means N stages fully hid behind each other.
        *Below* 1.0 the wall stage is dominated by something the work
        stages don't time — usually the host read leg (``ingest``) or
        dispatch gaps.  0.0 when the wall stage never ran.  See
        docs/WORKFLOWS.md "Diagnosing a slow link"."""
        wall_s = self.stages[wall].seconds if wall in self.stages else 0.0
        work_s = sum(
            self.stages[k].seconds for k in work if k in self.stages
        )
        eff = work_s / wall_s if wall_s > 0 else 0.0
        self.gauge(f"overlap.{wall}", eff)
        return eff

    def report(self, include_faults: bool = False) -> Dict[str, Dict]:
        out = {}
        # list(): producer threads (the window feeds) insert stage keys
        # concurrently with consumer-side reporting — never iterate the
        # live dict (CPython raises on resize-mid-iteration).  Torn
        # per-stage reads are acceptable for reporting.
        for k, v in sorted(list(self.stages.items())):
            row = {"calls": v.calls, "seconds": round(v.seconds, 6),
                   "bytes": v.bytes, "gbps": round(v.gbps, 3)}
            if v.byte_free:
                row["byte_free"] = True
            out[k] = row
        if self.gauges:
            out["gauges"] = {
                k: {"last": round(g.last, 6), "lo": round(g.lo, 6),
                    "hi": round(g.hi, 6), "n": g.n}
                for k, g in sorted(list(self.gauges.items()))
            }
        if include_faults:
            # Process-wide failure/recovery totals (blit/faults.py):
            # retry.io / retry.remote / mask.antenna / breaker.trip /
            # fault.<point>.<mode>.  Global (not per-timeline) by design —
            # retries deep inside the I/O layer have no timeline in hand.
            from blit import faults

            c = faults.counters()
            if c:
                out["faults"] = c
        return out

    def snapshot(self) -> Dict[str, tuple]:
        """Cheap point-in-time stage counters, for :meth:`since`
        (safe against concurrent producer-thread stage insertion)."""
        return {k: (v.calls, v.seconds, v.bytes)
                for k, v in list(self.stages.items())}

    def since(self, snap: Dict[str, tuple]) -> Dict[str, Dict]:
        """Per-stage deltas since a :meth:`snapshot` — the per-window stage
        record the windowed drivers report (seconds/bytes spent in each
        stage by ONE window, not the whole run)."""
        out = {}
        for k, v in list(self.stages.items()):
            c0, s0, b0 = snap.get(k, (0, 0.0, 0))
            if v.calls != c0 or v.bytes != b0 or v.seconds != s0:
                out[k] = {"calls": v.calls - c0,
                          "seconds": round(v.seconds - s0, 6),
                          "bytes": v.bytes - b0}
        return out

    def log(self, logger: Optional[logging.Logger] = None) -> None:
        (logger or logging.getLogger("blit.timeline")).info(
            "timeline %s", json.dumps(self.report())
        )


@contextlib.contextmanager
def profile_trace(logdir: Optional[str]) -> Iterator[None]:
    """JAX profiler trace around a region (TensorBoard/Perfetto readable).
    ``logdir=None`` is a no-op, so call sites need no conditionals."""
    if logdir is None:
        yield
        return
    import jax

    with jax.profiler.trace(logdir):
        yield


class HostContextFilter(logging.Filter):
    """Injects ``host`` and ``worker`` fields into every record so the
    fan-out logs stay attributable (the reference stamps host into every
    inventory row for the same reason, src/gbtworkerfunctions.jl:74)."""

    def __init__(self, worker: int = 0):
        super().__init__()
        self.host = socket.gethostname()
        self.worker = worker

    def filter(self, record: logging.LogRecord) -> bool:
        record.host = self.host
        record.worker = self.worker
        return True


def configure_logging(level: int = logging.INFO, worker: int = 0) -> None:
    """Structured stderr logging with host/worker context for every blit
    logger.  Idempotent: re-calling replaces the previous blit handler (a
    worker re-configuring with its id must not duplicate output)."""
    root = logging.getLogger("blit")
    for h in list(root.handlers):
        if getattr(h, "_blit_handler", False):
            root.removeHandler(h)
    handler = logging.StreamHandler()
    handler._blit_handler = True
    handler.addFilter(HostContextFilter(worker))
    handler.setFormatter(
        logging.Formatter(
            "%(asctime)s %(levelname)s %(host)s/w%(worker)d %(name)s: %(message)s"
        )
    )
    root.setLevel(level)
    root.addHandler(handler)
    # Our handler owns blit output; don't duplicate through root handlers.
    root.propagate = False
