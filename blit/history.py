"""Fleet history & incident forensics plane (ISSUE 20 tentpole).

The monitoring plane (blit/monitor.py) pages and load-sheds in the
moment; the request plane traces every hop — but all of it is
ephemeral.  This module makes the fleet's telemetry *durable* and an
incident *reconstructable from one artifact*:

- :class:`HistoryStore` — an RRD-style tiered ring store fed by
  :class:`~blit.monitor.MetricsPublisher` ticks.  Each tier is one
  fixed-size file of fixed-width slots (raw interval → minutes →
  hours buckets); a bucket record folds the tick deltas that landed in
  its window — stage calls/seconds/bytes, raw histogram states
  (reusing the ``HistogramStats.state`` merge discipline, so fleet
  series fold commutatively), gauge envelopes and per-objective SLO
  ``(bad, total)`` observations.  Slots are addressed by time
  (``(t0 // bucket_s) % slots``), so oldest-bucket overwrite is the
  file layout, the on-disk budget is fixed at creation, a reader can
  tail the rings while the writer runs (a torn slot heals and counts),
  and a restarted process re-adopts its partial bucket.

- :class:`AnomalyDetector` — a rolling median/MAD baseline per stored
  series, scored each publisher tick.  A robust z-score that stays
  past the sensitivity for N consecutive ticks pages through the
  EXISTING flight-dump machinery as a new ``"anomaly"`` breach class —
  the 20%-per-day p99 creep a static SLO threshold is structurally
  blind to.  ``BLIT_HISTORY_ANOMALY=0`` is the kill switch;
  ``BLIT_HISTORY_SENSITIVITY=metric=z,...`` tunes per metric.

- :class:`IncidentBundler` — on any page (SLO breach, anomaly, fleet
  eject, recover abort) snapshot ONE self-contained bundle directory:
  manifest + the relevant history window + matching request-log
  records + the stitched exemplar trace + a flight dump + ``/healthz``
  + config/tuning provenance.  ``blit incidents`` lists bundles;
  ``blit incident show`` renders a merged cross-source timeline,
  wall-clock aligned via the :func:`~blit.observability.wall_anchor`
  pairs stamped on every artifact.

- :func:`slo_report` — attainment and error-budget spend per objective
  over day/week windows straight from the store, text + JSON; the JSON
  carries a flat ``metrics`` dict with ``*_attained`` keys, so
  :func:`blit.monitor.bench_metrics` ingests it and ``blit bench-diff``
  can gate attainment like any other bench scalar.

Import discipline: stdlib + :mod:`blit.config` +
:mod:`blit.observability` at module level (the monitor rule — ``blit
incidents`` never pays the jax import); :mod:`blit.monitor` only
lazily, inside functions, so the two planes can reference each other
without a cycle.
"""

from __future__ import annotations

import glob
import json
import logging
import os
import re
import threading
import time
from collections import deque
from typing import Callable, Dict, Iterable, List, Optional, Tuple

from blit.config import DEFAULT, SiteConfig, history_defaults
from blit.observability import (
    HistogramStats,
    Timeline,
    flight_recorder,
    hostname,
    process_timeline,
    wall_anchor,
)

log = logging.getLogger("blit.history")

_MAGIC = "blh1"
# One padded header line per ring file; slots start right after it.
_HDR_BYTES = 256


# -- window grammar ----------------------------------------------------------

_WINDOW_RE = re.compile(r"^([0-9]*\.?[0-9]+)\s*(s|m|h|d|w)$", re.IGNORECASE)
_WINDOW_UNITS = {"s": 1.0, "m": 60.0, "h": 3600.0, "d": 86400.0,
                 "w": 604800.0}


def window_seconds(spec: str) -> float:
    """A window spec as seconds: ``"90"``/``"90s"``/``"15m"``/``"2h"``/
    ``"1d"``/``"1w"`` — the one grammar shared by ``blit incident
    show``, ``blit requests --since/--until``, ``blit slo-report
    --window`` and ``blit top --history``."""
    s = str(spec).strip()
    m = _WINDOW_RE.match(s)
    if m:
        return float(m.group(1)) * _WINDOW_UNITS[m.group(2).lower()]
    return float(s)


def parse_when(spec: str, now: Optional[float] = None) -> float:
    """A point in time: ``"now"``, an absolute epoch (values >= 1e9 —
    no window is 31 years long), or a window spec meaning "that long
    AGO" (``--since 15m`` = 15 minutes before now)."""
    now = time.time() if now is None else now
    s = str(spec).strip().lower()
    if s == "now":
        return now
    try:
        v = float(s)
        if v >= 1e9:
            return v
    except ValueError:
        pass
    return now - window_seconds(spec)


# -- bucket records and their folds ------------------------------------------
#
# A bucket record is plain JSON:
#   {"t0": <bucket start epoch>, "bucket_s": <width>, "n": <ticks>,
#    "seconds": <covered interval seconds>,
#    "stages": {name: {"calls", "seconds", "bytes"}},
#    "hists":  {name: HistogramStats.state() minus exemplars},
#    "gauges": {name: {"last", "lo", "hi", "n"}},
#    "burn":   {objective: {"bad", "total"}}}
# Every fold below is commutative and associative (sums / envelope
# widening), so tier downsampling, restart re-adoption and the fleet
# merge all conserve counts and sums exactly.


def _norm_hist_state(st: Dict) -> Dict:
    """A hist state stripped to its mergeable core (exemplars are
    "most recent", not summable — they stay in spools/flight dumps)."""
    return {"counts": [int(c) for c in (st.get("counts") or [])],
            "n": int(st.get("n", 0)), "total": float(st.get("total", 0.0)),
            "vmin": float(st.get("vmin", 0.0)),
            "vmax": float(st.get("vmax", 0.0))}


def _merge_hist_state(a: Optional[Dict], b: Optional[Dict]
                      ) -> Optional[Dict]:
    if b is None:
        return a
    b = _norm_hist_state(b)
    if a is None or not a.get("n"):
        return b if b["n"] else (b if a is None else a)
    if not b["n"]:
        return a
    counts = list(a.get("counts") or [])
    bc = b["counts"]
    if len(counts) < len(bc):
        counts.extend([0] * (len(bc) - len(counts)))
    for i, c in enumerate(bc):
        counts[i] += c
    return {"counts": counts, "n": a["n"] + b["n"],
            "total": float(a.get("total", 0.0)) + b["total"],
            "vmin": min(float(a.get("vmin", 0.0)), b["vmin"]),
            "vmax": max(float(a.get("vmax", 0.0)), b["vmax"])}


def _new_bucket(t0: float, bucket_s: float) -> Dict:
    return {"t0": t0, "bucket_s": bucket_s, "n": 0, "seconds": 0.0,
            "stages": {}, "hists": {}, "gauges": {}, "burn": {}}


def _fold_bucket(acc: Dict, *, interval_s: float = 0.0,
                 stages: Optional[Dict] = None,
                 hists: Optional[Dict] = None,
                 gauges: Optional[Dict] = None,
                 burn: Optional[Dict] = None, n: int = 1) -> Dict:
    """Fold one tick's (or one peer bucket's) contributions into
    ``acc`` in place.  ``stages``/``burn`` values are plain dicts;
    ``hists`` values are hist-state dicts; ``gauges`` values are either
    plain floats (a tick's level sample) or envelope dicts (a peer
    bucket's)."""
    acc["n"] = int(acc.get("n", 0)) + int(n)
    acc["seconds"] = float(acc.get("seconds", 0.0)) + float(interval_s)
    for k, row in (stages or {}).items():
        d = acc["stages"].setdefault(
            k, {"calls": 0, "seconds": 0.0, "bytes": 0})
        d["calls"] += int(row.get("calls", 0))
        d["seconds"] += float(row.get("seconds", 0.0))
        d["bytes"] += int(row.get("bytes", 0))
    for k, st in (hists or {}).items():
        acc["hists"][k] = _merge_hist_state(acc["hists"].get(k), st)
    for k, v in (gauges or {}).items():
        g = acc["gauges"].get(k)
        if isinstance(v, dict):
            lo, hi = float(v.get("lo", 0.0)), float(v.get("hi", 0.0))
            last, gn = float(v.get("last", 0.0)), int(v.get("n", 0))
        else:
            lo = hi = last = float(v)
            gn = 1
        if not gn:
            continue
        if g is None or not g.get("n"):
            acc["gauges"][k] = {"last": last, "lo": lo, "hi": hi, "n": gn}
        else:
            g["last"] = last
            g["lo"] = min(float(g["lo"]), lo)
            g["hi"] = max(float(g["hi"]), hi)
            g["n"] = int(g["n"]) + gn
    for name, row in (burn or {}).items():
        b = acc["burn"].setdefault(name, {"bad": 0, "total": 0})
        if isinstance(row, dict):
            b["bad"] += int(row.get("bad", 0))
            b["total"] += int(row.get("total", 0))
        else:
            bad, total = row
            b["bad"] += int(bad)
            b["total"] += int(total)
    return acc


def merge_buckets(bucket_lists: Iterable[Iterable[Dict]]) -> List[Dict]:
    """Fold bucket records from several stores (two peers' rings, a
    door's fan-out) by ``(bucket_s, t0)`` — the fleet series fold.
    Commutative: counts, sums and burn observations add; gauge
    envelopes widen.  Returns records sorted by (bucket_s, t0)."""
    out: Dict[Tuple[float, float], Dict] = {}
    for recs in bucket_lists:
        for rec in recs or []:
            if not isinstance(rec, dict) or "t0" not in rec:
                continue
            key = (float(rec.get("bucket_s", 0.0)), float(rec["t0"]))
            acc = out.get(key)
            if acc is None:
                acc = out[key] = _new_bucket(key[1], key[0])
            _fold_bucket(acc, interval_s=float(rec.get("seconds", 0.0)),
                         stages=rec.get("stages"), hists=rec.get("hists"),
                         gauges=rec.get("gauges"), burn=rec.get("burn"),
                         n=int(rec.get("n", 0)))
    return [out[k] for k in sorted(out)]


def bucket_point(rec: Dict, metric: str) -> Optional[Dict]:
    """Project one bucket record onto one metric — the query/sparkline
    value: a stage yields its bucket GB/s (calls for byte-free
    counters), a histogram its p99 (+ n/total), a gauge its envelope,
    ``slo.<objective>`` its bad fraction."""
    t0 = float(rec.get("t0", 0.0))
    base = {"t0": t0, "bucket_s": float(rec.get("bucket_s", 0.0))}
    st = (rec.get("stages") or {}).get(metric)
    if st is not None:
        secs = float(st.get("seconds", 0.0))
        nbytes = int(st.get("bytes", 0))
        gbps = nbytes / secs / 1e9 if secs > 0 and nbytes else 0.0
        base.update(kind="stage", calls=int(st.get("calls", 0)),
                    seconds=secs, bytes=nbytes, gbps=round(gbps, 4),
                    value=round(gbps, 4) if nbytes else
                    float(st.get("calls", 0)))
        return base
    hs = (rec.get("hists") or {}).get(metric)
    if hs is not None:
        h = HistogramStats.from_state(hs)
        base.update(kind="hist", n=h.n, total=h.total,
                    p50=round(h.percentile(0.50), 6),
                    p99=round(h.percentile(0.99), 6),
                    max=round(h.vmax, 6),
                    value=round(h.percentile(0.99), 6))
        return base
    g = (rec.get("gauges") or {}).get(metric)
    if g is not None:
        base.update(kind="gauge", last=float(g.get("last", 0.0)),
                    lo=float(g.get("lo", 0.0)), hi=float(g.get("hi", 0.0)),
                    n=int(g.get("n", 0)), value=float(g.get("last", 0.0)))
        return base
    if metric.startswith("slo."):
        b = (rec.get("burn") or {}).get(metric[4:])
        if b is not None:
            total = int(b.get("total", 0))
            frac = int(b.get("bad", 0)) / total if total else 0.0
            base.update(kind="slo", bad=int(b.get("bad", 0)), total=total,
                        value=round(frac, 6))
            return base
    return None


# -- the tiered slot-ring files ----------------------------------------------


class TierSpec:
    """One ring tier: ``slots`` fixed-width buckets of ``bucket_s``
    seconds, so the tier retains ``slots * bucket_s`` seconds and its
    file occupies ``_HDR_BYTES + slots * slot_bytes`` forever."""

    __slots__ = ("name", "bucket_s", "slots")

    def __init__(self, name: str, bucket_s: float, slots: int):
        self.name = str(name)
        self.bucket_s = float(bucket_s)
        self.slots = max(2, int(slots))
        if self.bucket_s <= 0:
            raise ValueError(f"tier {name}: bucket_s must be > 0")

    @property
    def retention_s(self) -> float:
        return self.bucket_s * self.slots


def history_tiers(d: Dict) -> List[TierSpec]:
    """The configured raw → mid → slow tier ladder
    (:func:`blit.config.history_defaults` dict in, specs out)."""
    return [TierSpec("raw", d["raw_s"], d["raw_slots"]),
            TierSpec("mid", d["mid_s"], d["mid_slots"]),
            TierSpec("slow", d["slow_s"], d["slow_slots"])]


def _encode_slot(rec: Dict, slot_bytes: int) -> Tuple[bytes, bool]:
    """One slot image: compact JSON, space-padded, newline at the slot
    boundary (the rings stay line-oriented for emergency ``grep``).
    Records too big for a slot shed their largest blocks (hists, then
    gauges) and mark ``overflow`` — a partial bucket beats a torn
    one."""
    overflow = False
    data = json.dumps(rec, separators=(",", ":")).encode()
    if len(data) >= slot_bytes:
        overflow = True
        slim = dict(rec)
        slim["hists"] = {}
        slim["overflow"] = True
        data = json.dumps(slim, separators=(",", ":")).encode()
        if len(data) >= slot_bytes:
            slim["gauges"] = {}
            slim["stages"] = {}
            data = json.dumps(slim, separators=(",", ":")).encode()
    buf = data + b" " * (slot_bytes - len(data) - 1) + b"\n"
    return buf, overflow


def _parse_slot(blob: bytes):
    """``(record, torn)``: an all-zero/blank slot is empty (never
    written — not an error); a non-empty unparseable one is TORN (a
    writer died mid-``pwrite``) and heals to None, counted by the
    caller (the PR 19 backfill-ledger rule)."""
    s = blob.decode("utf-8", errors="replace").strip("\x00 \r\n\t")
    if not s:
        return None, False
    try:
        rec = json.loads(s)
    except ValueError:
        return None, True
    if not isinstance(rec, dict) or "t0" not in rec:
        return None, True
    return rec, False


def _read_header(f) -> Optional[Dict]:
    blob = f.read(_HDR_BYTES)
    if len(blob) < _HDR_BYTES:
        return None
    try:
        hdr = json.loads(blob.decode("utf-8", errors="replace").strip())
    except ValueError:
        return None
    if not isinstance(hdr, dict) or hdr.get("magic") != _MAGIC:
        return None
    return hdr


def read_ring(path: str, t0: Optional[float] = None,
              t1: Optional[float] = None) -> Tuple[Dict, List[Dict], int]:
    """Read one ring file: ``(header, records, torn_slots)``.  With a
    ``[t0, t1]`` window, only the slots whose time-addressed indices
    can hold it are visited (a ``blit top`` frame over a 2-hour raw
    ring reads a few KB, not the whole file); records are filtered to
    the window either way and come back t0-sorted.  Opens its own
    descriptor — safe to call while the owning publisher writes."""
    with open(path, "rb") as f:
        hdr = _read_header(f)
        if hdr is None:
            raise ValueError(f"{path} is not a blit history ring")
        bucket_s = float(hdr["bucket_s"])
        slots = int(hdr["slots"])
        slot_bytes = int(hdr["slot_bytes"])
        recs: List[Dict] = []
        torn = 0
        if t0 is not None and t1 is not None and \
                (t1 - t0) / bucket_s < slots - 1:
            first = int(t0 // bucket_s)
            last = int(t1 // bucket_s)
            indices = sorted({b % slots for b in range(first, last + 1)})
        else:
            indices = range(slots)
        for i in indices:
            f.seek(_HDR_BYTES + i * slot_bytes)
            rec, is_torn = _parse_slot(f.read(slot_bytes))
            if is_torn:
                torn += 1
                continue
            if rec is None:
                continue
            rt0 = float(rec.get("t0", 0.0))
            if t0 is not None and rt0 + bucket_s <= t0:
                continue
            if t1 is not None and rt0 > t1:
                continue
            recs.append(rec)
    recs.sort(key=lambda r: r.get("t0", 0.0))
    return hdr, recs, torn


class HistoryStore:
    """The durable tiered metric store (module docstring).  One
    instance is the single WRITER for its directory (the publisher
    holds it); readers use :meth:`buckets`/:meth:`series` on any
    instance (``create=False`` never touches disk layout) or the
    module-level :func:`read_history`.

    Every tick folds into ALL tiers' current buckets and writes each
    tier's partial bucket through to its slot — readers always see
    data at most one tick stale, a tick never costs more than three
    slot writes, and same-source folding makes tier-boundary
    counts/sums conservation exact (tests pin it)."""

    def __init__(self, dir: str, *, config: SiteConfig = DEFAULT,
                 tiers: Optional[List[TierSpec]] = None,
                 slot_bytes: Optional[int] = None,
                 clock: Callable[[], float] = time.time,
                 create: bool = True):
        d = history_defaults(config)
        self.dir = dir
        self.clock = clock
        self.tiers = list(tiers) if tiers is not None else history_tiers(d)
        self.slot_bytes = max(2048, int(slot_bytes if slot_bytes is not None
                                        else d["slot_bytes"]))
        self._lock = threading.Lock()
        self._f: Dict[str, object] = {}
        self._geom: Dict[str, Tuple[float, int, int]] = {}
        self._acc: Dict[str, Dict] = {}
        self.torn_slots = 0
        self.overflow_slots = 0
        if create:
            os.makedirs(self.dir, exist_ok=True)

    # -- tier files --------------------------------------------------------
    def _tier_path(self, name: str) -> str:
        return os.path.join(self.dir, f"{name}.ring")

    def _ensure_tier(self, tier: TierSpec) -> None:
        if tier.name in self._f:
            return
        path = self._tier_path(tier.name)
        if not os.path.exists(path):
            hdr = json.dumps({
                "magic": _MAGIC, "tier": tier.name,
                "bucket_s": tier.bucket_s, "slots": tier.slots,
                "slot_bytes": self.slot_bytes, "v": 1}).encode()
            buf = hdr + b" " * (_HDR_BYTES - len(hdr) - 1) + b"\n"
            tmp = path + ".tmp"
            with open(tmp, "wb") as f:
                f.write(buf)
                # The full budget is claimed up front: the file NEVER
                # grows after creation, whatever lands in it.
                f.truncate(_HDR_BYTES + tier.slots * self.slot_bytes)
            os.replace(tmp, path)
        f = open(path, "r+b")
        hdr = _read_header(f)
        if hdr is None:
            # Unrecognizable file at the tier path: refuse to write
            # through it (it may be someone else's data).
            f.close()
            raise ValueError(f"{path} exists but is not a history ring")
        # The FILE's geometry wins over the configured one (a restart
        # under different env must keep addressing old slots correctly).
        self._geom[tier.name] = (float(hdr["bucket_s"]), int(hdr["slots"]),
                                 int(hdr["slot_bytes"]))
        self._f[tier.name] = f

    def _write_slot(self, name: str, rec: Dict) -> None:
        bucket_s, slots, slot_bytes = self._geom[name]
        i = int(rec["t0"] // bucket_s) % slots
        buf, overflow = _encode_slot(rec, slot_bytes)
        if overflow:
            self.overflow_slots += 1
            process_timeline().count("history.slot_overflow")
        f = self._f[name]
        f.seek(_HDR_BYTES + i * slot_bytes)
        f.write(buf)
        f.flush()

    def _read_own_slot(self, name: str, t0: float) -> Optional[Dict]:
        bucket_s, slots, slot_bytes = self._geom[name]
        i = int(t0 // bucket_s) % slots
        f = self._f[name]
        f.seek(_HDR_BYTES + i * slot_bytes)
        rec, torn = _parse_slot(f.read(slot_bytes))
        if torn:
            self.torn_slots += 1
            process_timeline().count("history.torn_slot")
        if rec is not None and float(rec.get("t0", -1.0)) == float(t0):
            return rec
        return None

    # -- writing -----------------------------------------------------------
    def append(self, t: float, interval_s: float, delta: Timeline, *,
               gauges: Optional[Dict[str, float]] = None,
               burn: Optional[Dict[str, Tuple[int, int]]] = None) -> None:
        """Fold one publisher tick into every tier: ``delta`` is the
        interval's Timeline delta (stages + hists), ``gauges`` the
        current levels, ``burn`` the tick's per-objective ``(bad,
        total)`` SLO observations.  Each tier's live bucket is written
        through immediately (read-while-write freshness); a bucket
        whose window closed gets its final image flushed first."""
        stages = {k: {"calls": s.calls, "seconds": s.seconds,
                      "bytes": s.bytes}
                  for k, s in list(delta.stages.items())}
        hists = {k: _norm_hist_state(h.state())
                 for k, h in list(delta.hists.items()) if h.n}
        with self._lock:
            for tier in self.tiers:
                try:
                    self._ensure_tier(tier)
                except (OSError, ValueError):
                    log.warning("history tier %s unavailable", tier.name,
                                exc_info=True)
                    continue
                bucket_s = self._geom[tier.name][0]
                t0 = (t // bucket_s) * bucket_s
                acc = self._acc.get(tier.name)
                if acc is None or float(acc["t0"]) != t0:
                    if acc is not None:
                        self._write_slot(tier.name, acc)
                    # Restart mid-bucket: adopt the partial bucket the
                    # previous process wrote for this same window, so
                    # its ticks aren't zeroed by ours.
                    acc = (self._read_own_slot(tier.name, t0)
                           or _new_bucket(t0, bucket_s))
                    self._acc[tier.name] = acc
                _fold_bucket(acc, interval_s=interval_s, stages=stages,
                             hists=hists, gauges=gauges, burn=burn)
                self._write_slot(tier.name, acc)

    def merge_in(self, buckets: Iterable[Dict]) -> int:
        """Fold EXTERNAL bucket records (a peer's ``/history`` answer)
        into matching-width tiers — how a door materializes a fleet
        store.  Records whose width matches no local tier are skipped;
        returns the number folded."""
        folded = 0
        with self._lock:
            for rec in buckets:
                if not isinstance(rec, dict) or "t0" not in rec:
                    continue
                width = float(rec.get("bucket_s", 0.0))
                tier = next((tr for tr in self.tiers
                             if abs(tr.bucket_s - width) < 1e-9), None)
                if tier is None:
                    continue
                try:
                    self._ensure_tier(tier)
                except (OSError, ValueError):
                    continue
                t0 = float(rec["t0"])
                acc = self._acc.get(tier.name)
                if acc is not None and float(acc["t0"]) == t0:
                    target = acc
                else:
                    target = (self._read_own_slot(tier.name, t0)
                              or _new_bucket(t0, tier.bucket_s))
                _fold_bucket(target,
                             interval_s=float(rec.get("seconds", 0.0)),
                             stages=rec.get("stages"),
                             hists=rec.get("hists"),
                             gauges=rec.get("gauges"),
                             burn=rec.get("burn"),
                             n=int(rec.get("n", 0)))
                self._write_slot(tier.name, target)
                folded += 1
        return folded

    # -- reading -----------------------------------------------------------
    def _ring_headers(self) -> List[Tuple[str, Dict]]:
        out = []
        for path in sorted(glob.glob(os.path.join(self.dir, "*.ring"))):
            try:
                with open(path, "rb") as f:
                    hdr = _read_header(f)
            except OSError:
                continue
            if hdr is not None:
                out.append((path, hdr))
        return out

    def pick_tier(self, t0: float, now: Optional[float] = None
                  ) -> Optional[str]:
        """The FINEST tier whose retention still covers ``t0`` (the
        coarsest when none does) — query resolution degrades with age
        exactly the way the rings store it."""
        now = self.clock() if now is None else now
        rings = self._ring_headers()
        if not rings:
            return None
        rings.sort(key=lambda ph: float(ph[1]["bucket_s"]))
        for _, hdr in rings:
            if float(hdr["bucket_s"]) * int(hdr["slots"]) >= now - t0:
                return str(hdr["tier"])
        return str(rings[-1][1]["tier"])

    def buckets(self, t0: float, t1: Optional[float] = None, *,
                tier: Optional[str] = None) -> List[Dict]:
        """Raw bucket records over ``[t0, t1]`` from one tier (auto:
        :meth:`pick_tier`).  Torn slots heal and count."""
        t1 = self.clock() if t1 is None else t1
        name = tier or self.pick_tier(t0, now=t1)
        if name is None:
            return []
        path = self._tier_path(name)
        try:
            _, recs, torn = read_ring(path, t0, t1)
        except (OSError, ValueError):
            return []
        if torn:
            self.torn_slots += torn
            process_timeline().count("history.torn_slot", torn)
        return recs

    def series(self, metric: str, t0: float,
               t1: Optional[float] = None, *,
               tier: Optional[str] = None) -> List[Dict]:
        """The ``(metric, window)`` query surface: one point per bucket
        (:func:`bucket_point`), t0-sorted."""
        out = []
        for rec in self.buckets(t0, t1, tier=tier):
            p = bucket_point(rec, metric)
            if p is not None:
                out.append(p)
        return out

    def metrics(self, window_s: float = 3600.0) -> List[str]:
        """Names with data in the finest tier's recent window."""
        now = self.clock()
        names = set()
        for rec in self.buckets(now - window_s, now):
            names.update(rec.get("stages") or {})
            names.update(rec.get("hists") or {})
            names.update(rec.get("gauges") or {})
            names.update(f"slo.{k}" for k in rec.get("burn") or {})
        return sorted(names)

    def disk_usage(self) -> int:
        """Bytes the ring files occupy — fixed at creation, whatever
        gets written (the budget test pins this across a simulated
        week)."""
        total = 0
        for path, _ in self._ring_headers():
            try:
                total += os.path.getsize(path)
            except OSError:
                continue
        return total

    def close(self) -> None:
        with self._lock:
            for name, acc in list(self._acc.items()):
                if name in self._f:
                    try:
                        self._write_slot(name, acc)
                    except OSError:
                        pass
            for f in self._f.values():
                try:
                    f.close()
                except OSError:
                    pass
            self._f.clear()
            self._acc.clear()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def read_history(dir: str, metric: str, t0: float,
                 t1: Optional[float] = None,
                 tier: Optional[str] = None) -> List[Dict]:
    """Read-only one-shot query over a store directory (the CLI's
    path: never creates files)."""
    return HistoryStore(dir, create=False).series(metric, t0, t1,
                                                  tier=tier)


# -- anomaly baselines -------------------------------------------------------


def _robust_scale(base: List[float], med: float) -> float:
    """1.4826·MAD — the σ-consistent robust spread — floored at 5% of
    the median's magnitude.  The floor keeps quantized series honest:
    log2-bucket p99s collapse to a handful of interpolated values, so
    their MAD is near zero and any adjacent-bucket wobble would score
    as hundreds of sigmas.  Sub-5%-of-level deviations are never worth
    a page; a genuine step still clears the floor by orders of
    magnitude (and a dead-zero baseline keeps the 1e-9 epsilon)."""
    dev = sorted(abs(x - med) for x in base)
    n = len(dev)
    mad = (dev[n // 2] if n % 2 else (dev[n // 2 - 1] + dev[n // 2]) / 2.0)
    return max(1.4826 * mad, abs(med) * 0.05, 1e-9)


def _median(xs: List[float]) -> float:
    s = sorted(xs)
    n = len(s)
    return s[n // 2] if n % 2 else (s[n // 2 - 1] + s[n // 2]) / 2.0


def series_values(delta: Timeline,
                  gauges: Optional[Dict[str, float]] = None
                  ) -> Dict[str, float]:
    """One tick's scoreable series: per-stage GB/s (``<stage>.gbps``),
    per-histogram p99 (``<hist>.p99_s``), raw gauge levels.  Idle
    series contribute nothing (a paused pipeline is not an anomalous
    one — the SLO throughput rule)."""
    vals: Dict[str, float] = {}
    for k, s in list(delta.stages.items()):
        if s.seconds > 0 and s.bytes:
            vals[f"{k}.gbps"] = s.bytes / s.seconds / 1e9
    for k, h in list(delta.hists.items()):
        if h.n:
            vals[f"{k}.p99_s"] = h.percentile(0.99)
    for k, v in (gauges or {}).items():
        vals[k] = float(v)
    return vals


def _anomalous_sign(metric: str) -> float:
    """Which direction is bad: throughput series (``.gbps``) page on a
    DROP; latency/level series page on a RISE."""
    return -1.0 if metric.endswith(".gbps") else 1.0


class AnomalyDetector:
    """Rolling median/MAD baseline per series (module docstring).
    Each tick: score the incoming value against the PRIOR window
    (median ± 1.4826·MAD), then admit it.  A breach needs
    ``min_n`` history, a robust z past the metric's sensitivity in its
    bad direction, and ``consecutive`` such ticks in a row — one noisy
    sample never pages.  While a series stays in breach it does not
    re-page; recovery re-arms it.  Pages ride the existing flight-dump
    machinery (event + ``anomaly.breach.<metric>`` counter + dump,
    first-per-metric forced) as alert class ``"anomaly"``."""

    def __init__(self, *, z: float = 6.0, window: int = 120,
                 min_n: int = 30, consecutive: int = 3,
                 overrides: Optional[Dict[str, float]] = None,
                 recorder=None,
                 clock: Callable[[], float] = time.time):
        self.z = float(z)
        self.window = max(4, int(window))
        self.min_n = max(3, int(min_n))
        self.consecutive = max(1, int(consecutive))
        self.overrides = dict(overrides or {})
        self.recorder = recorder
        self.clock = clock
        self._hist: Dict[str, deque] = {}
        self._streak: Dict[str, int] = {}
        self._breached: Dict[str, Dict] = {}
        self._dumped: set = set()
        self.alerts: List[Dict] = []

    @classmethod
    def for_config(cls, config: SiteConfig = DEFAULT, **kw
                   ) -> "AnomalyDetector":
        d = history_defaults(config)
        return cls(z=d["anomaly_z"], window=d["anomaly_window"],
                   min_n=d["anomaly_min_n"],
                   consecutive=d["anomaly_consecutive"],
                   overrides=d["anomaly_overrides"], **kw)

    def threshold_for(self, metric: str) -> float:
        return float(self.overrides.get(metric, self.z))

    def observe(self, values: Dict[str, float],
                t: Optional[float] = None) -> List[Dict]:
        """Score one tick's series values; returns the alerts raised."""
        t = self.clock() if t is None else t
        fired: List[Dict] = []
        for metric in sorted(values):
            v = float(values[metric])
            dq = self._hist.get(metric)
            if dq is None:
                dq = self._hist[metric] = deque(maxlen=self.window)
            base = list(dq)
            dq.append(v)
            if len(base) < self.min_n:
                continue
            med = _median(base)
            scale = _robust_scale(base, med)
            z = _anomalous_sign(metric) * (v - med) / scale
            thr = self.threshold_for(metric)
            if z < thr:
                self._streak[metric] = 0
                if metric in self._breached:
                    self._breached.pop(metric, None)
                    log.info("anomaly cleared: %s", metric)
                continue
            # Over threshold: a breached series stays breached without
            # re-paging (and without poisoning its own baseline — the
            # anomalous value was already admitted to the window, but
            # the window is long enough that recovery wins).
            if metric in self._breached:
                continue
            streak = self._streak.get(metric, 0) + 1
            self._streak[metric] = streak
            if streak < self.consecutive:
                continue
            self._streak[metric] = 0
            alert = {"t": t, "class": "anomaly", "metric": metric,
                     "value": round(v, 6), "baseline": round(med, 6),
                     "scale": round(scale, 6), "z": round(z, 2),
                     "threshold": thr, "window": len(base),
                     "consecutive": self.consecutive}
            self._breached[metric] = alert
            rec = self.recorder if self.recorder is not None \
                else flight_recorder()
            rec.event("anomaly", metric, z=round(z, 2),
                      baseline=round(med, 6), value=round(v, 6))
            process_timeline().count(f"anomaly.breach.{metric}")
            path = rec.dump(
                f"anomaly: {metric} at {v:.6g} is {z:.1f} robust sigmas "
                f"past its rolling median {med:.6g} for "
                f"{self.consecutive} consecutive ticks",
                force=metric not in self._dumped,
                key=f"anomaly:{metric}")
            self._dumped.add(metric)
            if path:
                alert["flight_dump"] = path
            self.alerts.append(alert)
            del self.alerts[:-256]
            fired.append(alert)
            log.warning("anomaly breach: %s z=%.1f (baseline %.6g, "
                        "value %.6g)", metric, z, med, v)
        return fired

    def breached(self) -> List[str]:
        return sorted(self._breached)

    def report(self) -> Dict[str, Dict]:
        """Currently-breached series (the sample's ``anomaly`` block —
        compact: quiet baselines ship nothing)."""
        return {k: dict(a) for k, a in self._breached.items()}


# -- incident bundles --------------------------------------------------------

_SLUG_RE = re.compile(r"[^A-Za-z0-9_.-]+")


def _slug(s: str) -> str:
    return (_SLUG_RE.sub("-", str(s)).strip("-") or "incident")[:48]


class IncidentBundler:
    """One self-contained bundle directory per page (module
    docstring).  Rate-limited per incident KIND (first per kind
    forced — the FlightRecorder discipline), so an alert storm writes
    one bundle, not hundreds.  :meth:`snapshot` never raises: the
    caller is already mid-incident."""

    def __init__(self, dir: str, *, window_s: float = 900.0,
                 cooldown_s: float = 300.0,
                 config: SiteConfig = DEFAULT,
                 clock: Callable[[], float] = time.time):
        self.dir = dir
        self.window_s = float(window_s)
        self.cooldown_s = float(cooldown_s)
        self.config = config
        self.clock = clock
        self._lock = threading.Lock()
        self._last: Dict[str, float] = {}
        self._seq = 0

    def _resolve_trace(self, timeline: Optional[Timeline],
                       alert: Optional[Dict]) -> Optional[str]:
        """The exemplar trace id the bundle pivots on: the breached
        metric's tail exemplar when the alert names one, else the
        newest tail exemplar of any request-ish histogram, else the
        newest finished span's trace."""
        candidates: List[Tuple[float, int, str]] = []
        if timeline is not None:
            metric = (alert or {}).get("metric", "")
            for k, h in list(timeline.hists.items()):
                ex = h.tail_exemplar()
                if not ex:
                    continue
                pri = 2 if (metric and metric.startswith(k)) else (
                    1 if "request" in k else 0)
                candidates.append((float(ex.get("t", 0.0)), pri,
                                   str(ex["trace"])))
        if candidates:
            candidates.sort(key=lambda c: (c[1], c[0]))
            return candidates[-1][2]
        from blit import observability

        spans = observability.tracer().span_dicts()
        for sp in reversed(spans):
            if sp.get("trace"):
                return str(sp["trace"])
        return None

    def snapshot(self, kind: str, reason: str, *,
                 alert: Optional[Dict] = None,
                 publisher=None,
                 timeline: Optional[Timeline] = None,
                 history: Optional[HistoryStore] = None,
                 force: bool = False) -> Optional[str]:
        """Write one bundle; returns its directory path, or None when
        rate-limited/disabled.  ``publisher`` (a MetricsPublisher)
        supplies ``/healthz`` + the merged timeline; a bare
        ``timeline`` works for publisher-less callers (the fleet
        door)."""
        if os.environ.get("BLIT_FLIGHT_DISABLE"):
            return None
        try:
            now = self.clock()
            kslug = _slug(kind)
            with self._lock:
                last = self._last.get(kslug)
                if (last is not None and not force
                        and now - last < self.cooldown_s):
                    return None
                self._last[kslug] = now
                self._seq += 1
                seq = self._seq
            stamp = time.strftime("%Y%m%dT%H%M%SZ", time.gmtime(now))
            path = os.path.join(
                self.dir, f"incident-{stamp}-{kslug}-{hostname()}-"
                          f"{os.getpid()}-{seq}")
            os.makedirs(path, exist_ok=True)
            tl = timeline
            if tl is None and publisher is not None:
                tl = publisher.merged_timeline()
            if tl is None:
                tl = process_timeline()
            trace = self._resolve_trace(tl, alert)
            t0 = now - self.window_s
            # Flight dump FIRST (forced, explicit path): the ring's
            # recent events are the most perishable evidence.
            flight_recorder().dump(reason,
                                   path=os.path.join(path, "flight.json"),
                                   force=True)
            self._write_json(path, "healthz.json",
                             publisher.health() if publisher is not None
                             else {"t": now, "host": hostname(),
                                   "pid": os.getpid(), "ok": False,
                                   "status": "incident",
                                   "reasons": [kind]})
            self._write_history(path, history, t0, now)
            n_req = self._write_requests(path, t0, now)
            self._write_trace(path, trace)
            manifest = {
                "kind": kind, "reason": reason, "t": now,
                "t0": t0, "window_s": self.window_s,
                "host": hostname(), "pid": os.getpid(),
                "anchor": wall_anchor(),
                "alert": alert, "trace": trace,
                "requests": n_req,
                "files": sorted(os.listdir(path)) + ["incident.json"],
                "provenance": self._provenance(),
            }
            # The manifest lands LAST — a bundle without incident.json
            # is in-progress/aborted and `blit incidents` skips it.
            self._write_json(path, "incident.json", manifest)
            log.error("incident bundle written to %s (%s)", path, reason)
            return path
        except Exception:  # noqa: BLE001 — never mask the real incident
            log.warning("incident bundle failed", exc_info=True)
            return None

    # -- bundle members ----------------------------------------------------
    @staticmethod
    def _write_json(path: str, name: str, doc) -> None:
        tmp = os.path.join(path, name + ".tmp")
        with open(tmp, "w") as f:
            json.dump(doc, f)
        os.replace(tmp, os.path.join(path, name))

    def _write_history(self, path: str, history: Optional[HistoryStore],
                       t0: float, t1: float) -> None:
        buckets: List[Dict] = []
        metrics: List[str] = []
        if history is not None:
            try:
                buckets = history.buckets(t0, t1)
                metrics = history.metrics(window_s=t1 - t0)
            except Exception:  # noqa: BLE001 — partial bundle beats none
                log.warning("incident history read failed", exc_info=True)
        self._write_json(path, "history.json",
                         {"t0": t0, "t1": t1, "buckets": buckets,
                          "metrics": metrics})

    def _write_requests(self, path: str, t0: float, t1: float) -> int:
        from blit.config import request_log_defaults
        from blit.monitor import read_requests

        d = request_log_defaults(self.config)["dir"]
        records: List[Dict] = []
        if d and os.path.isdir(d):
            try:
                records = [r for r in read_requests(d)
                           if t0 <= float(r.get("t", 0.0)) <= t1 + 1.0]
            except Exception:  # noqa: BLE001
                log.warning("incident request read failed", exc_info=True)
        tmp = os.path.join(path, "requests.jsonl.tmp")
        with open(tmp, "w") as f:
            for r in records:
                f.write(json.dumps(r) + "\n")
        os.replace(tmp, os.path.join(path, "requests.jsonl"))
        return len(records)

    def _write_trace(self, path: str, trace: Optional[str]) -> None:
        from blit import observability

        spans = observability.tracer().span_dicts()[-512:]
        self._write_json(path, "trace.json",
                         {"trace": trace,
                          "spans": spans,
                          "trace_spans": [s for s in spans
                                          if s.get("trace") == trace]})

    def _provenance(self) -> Dict:
        """Config/tuning provenance: which knobs shaped the paging
        process — the effective defaults dicts plus every BLIT_* env
        override and the tuner's state."""
        from blit.config import monitor_defaults, slo_defaults

        prov: Dict = {
            "history": {k: v for k, v in
                        history_defaults(self.config).items()},
            "monitor": monitor_defaults(self.config),
            "slo": slo_defaults(self.config),
            "env": {k: v for k, v in sorted(os.environ.items())
                    if k.startswith("BLIT_")},
        }
        try:
            from blit import tune

            prov["tune"] = {"enabled": tune.enabled(),
                            "dir": tune.profile_dir(self.config)}
        except Exception:  # noqa: BLE001
            pass
        return prov


# -- the process-wide bundler + page hook ------------------------------------

_BUNDLER: Optional[IncidentBundler] = None
_BUNDLER_LOCK = threading.Lock()


def incident_bundler(config: SiteConfig = DEFAULT
                     ) -> Optional[IncidentBundler]:
    """The process-wide bundler (None while ``BLIT_INCIDENT_DIR`` /
    ``SiteConfig.incident_dir`` is unset — disabled costs one dict
    lookup)."""
    global _BUNDLER
    d = history_defaults(config)
    if not d["incident_dir"]:
        return None
    with _BUNDLER_LOCK:
        if _BUNDLER is None or _BUNDLER.dir != d["incident_dir"]:
            _BUNDLER = IncidentBundler(
                d["incident_dir"], window_s=d["incident_window_s"],
                cooldown_s=d["incident_cooldown_s"], config=config)
        return _BUNDLER


def reset_bundler() -> None:
    """Forget the process-wide bundler (tests flip the env per run)."""
    global _BUNDLER
    with _BUNDLER_LOCK:
        _BUNDLER = None


def maybe_incident(kind: str, reason: str, *,
                   alert: Optional[Dict] = None,
                   publisher=None,
                   timeline: Optional[Timeline] = None,
                   history: Optional[HistoryStore] = None,
                   config: SiteConfig = DEFAULT,
                   force: bool = False) -> Optional[str]:
    """The one page hook every plane calls (fleet eject, recover
    abort, SLO/anomaly breach): bundle if bundling is on.  Never
    raises."""
    try:
        b = incident_bundler(config)
        if b is None:
            return None
        return b.snapshot(kind, reason, alert=alert, publisher=publisher,
                          timeline=timeline, history=history, force=force)
    except Exception:  # noqa: BLE001 — paging must not break the plane
        log.warning("maybe_incident failed", exc_info=True)
        return None


# -- bundle reading / rendering ----------------------------------------------


def list_incidents(dir: str) -> List[Dict]:
    """Bundle manifests under ``dir``, oldest first.  Directories
    without a committed ``incident.json`` (in-progress/aborted) are
    skipped; unreadable manifests are skipped and counted."""
    out: List[Dict] = []
    for path in sorted(glob.glob(os.path.join(dir, "incident-*"))):
        mpath = os.path.join(path, "incident.json")
        if not os.path.isfile(mpath):
            continue
        try:
            with open(mpath) as f:
                m = json.load(f)
        except (OSError, ValueError):
            process_timeline().count("history.torn_manifest")
            continue
        if isinstance(m, dict):
            m["path"] = path
            out.append(m)
    out.sort(key=lambda m: m.get("t", 0.0))
    return out


def load_incident(path: str) -> Dict:
    """Everything in one bundle, reading ONLY inside its directory
    (the self-containment contract the CI drill pins): manifest,
    flight dump, history window, request records (torn lines heal and
    count), trace doc, healthz."""
    def read_json(name):
        try:
            with open(os.path.join(path, name)) as f:
                return json.load(f)
        except (OSError, ValueError):
            return None

    requests: List[Dict] = []
    torn = 0
    try:
        with open(os.path.join(path, "requests.jsonl")) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    doc = json.loads(line)
                except ValueError:
                    torn += 1
                    continue
                if isinstance(doc, dict):
                    requests.append(doc)
    except OSError:
        pass
    if torn:
        process_timeline().count("monitor.torn_lines", torn)
    return {"path": path,
            "manifest": read_json("incident.json") or {},
            "flight": read_json("flight.json"),
            "history": read_json("history.json"),
            "trace": read_json("trace.json"),
            "healthz": read_json("healthz.json"),
            "requests": requests,
            "torn_lines": torn}


def render_incidents(manifests: List[Dict]) -> str:
    lines = [f"{'when (UTC)':<20} {'kind':<16} {'reqs':>5} "
             f"{'trace':<18} reason"]
    for m in manifests:
        when = time.strftime("%Y-%m-%d %H:%M:%S",
                             time.gmtime(m.get("t", 0.0)))
        lines.append(
            f"{when:<20} {str(m.get('kind', '?')):<16} "
            f"{m.get('requests', 0):>5} "
            f"{str(m.get('trace') or '-'):<18} "
            f"{str(m.get('reason', ''))[:60]}")
        lines.append(f"  {m.get('path', '')}")
    if not manifests:
        lines.append("(no incident bundles)")
    return "\n".join(lines)


def incident_timeline(bundle: Dict,
                      window: Optional[Tuple[float, float]] = None
                      ) -> List[Tuple[float, str, str]]:
    """The merged cross-source event list of one bundle: flight-ring
    events, request records, trace spans and the triggering alert,
    each as ``(epoch t, source, text)``, wall-clock sorted.  All
    sources already stamp epoch seconds; the manifest/flight anchors
    tell the reader how much to trust cross-process alignment
    (rendered by :func:`render_incident`)."""
    events: List[Tuple[float, str, str]] = []
    m = bundle.get("manifest") or {}
    if m.get("t"):
        events.append((float(m["t"]), "page",
                       f"{m.get('kind')}: {m.get('reason', '')}"))
    alert = m.get("alert")
    if isinstance(alert, dict) and alert.get("t"):
        desc = " ".join(f"{k}={alert[k]}" for k in
                        ("class", "objective", "metric", "z", "burn_fast")
                        if alert.get(k) is not None)
        events.append((float(alert["t"]), "alert", desc))
    for e in ((bundle.get("flight") or {}).get("events") or []):
        rest = {k: v for k, v in e.items()
                if k not in ("t", "kind", "name")}
        detail = " ".join(f"{k}={v}" for k, v in rest.items())
        events.append((float(e.get("t", 0.0)), f"flight/{e.get('kind')}",
                       f"{e.get('name', '?')} {detail}".rstrip()))
    for r in bundle.get("requests") or []:
        events.append((
            float(r.get("t", 0.0)), "request",
            f"{r.get('role', '?')} {r.get('status', '?')} "
            f"{r.get('duration_s', 0.0) * 1e3:.1f}ms "
            f"client={r.get('client', '-')} trace={r.get('trace', '-')}"))
    for s in ((bundle.get("trace") or {}).get("trace_spans") or []):
        events.append((
            float(s.get("t0", 0.0)), "span",
            f"{s.get('name', '?')} {s.get('duration_s', 0.0) * 1e3:.1f}ms "
            f"span={s.get('span', '-')}"))
    if window is not None:
        t0, t1 = window
        events = [e for e in events if t0 <= e[0] <= t1]
    events.sort(key=lambda e: e[0])
    return events


def render_incident(bundle: Dict,
                    window: Optional[Tuple[float, float]] = None) -> str:
    """``blit incident show``'s body: the manifest header (anchor
    included — the cross-process alignment evidence), the breached
    metric's history sparkline, and the merged timeline."""
    m = bundle.get("manifest") or {}
    lines = ["=== blit incident bundle ==="]
    when = time.strftime("%Y-%m-%d %H:%M:%S", time.gmtime(m.get("t", 0.0)))
    lines.append(f"kind   : {m.get('kind', '?')}")
    lines.append(f"reason : {m.get('reason', '?')}")
    lines.append(f"when   : {when} UTC  (window {m.get('window_s', 0)}s)")
    lines.append(f"where  : {m.get('host', '?')} pid {m.get('pid', '?')}")
    anchor = m.get("anchor") or {}
    if anchor:
        origin = anchor.get("epoch", 0.0) - anchor.get("mono", 0.0)
        lines.append(f"anchor : epoch={anchor.get('epoch')} "
                     f"mono={anchor.get('mono')} "
                     f"(mono origin {origin:.3f})")
        flight_anchor = (bundle.get("flight") or {}).get("anchor") or {}
        if flight_anchor:
            skew = ((flight_anchor.get("epoch", 0.0)
                     - flight_anchor.get("mono", 0.0)) - origin)
            lines.append(f"         flight-dump anchor skew {skew:+.3f}s")
    if m.get("trace"):
        n_spans = len((bundle.get("trace") or {}).get("trace_spans") or [])
        n_req = sum(1 for r in bundle.get("requests") or []
                    if r.get("trace") == m["trace"])
        lines.append(f"trace  : {m['trace']} ({n_spans} span(s), "
                     f"{n_req} request record(s) in bundle)")
    alert = m.get("alert")
    if isinstance(alert, dict):
        desc = " ".join(f"{k}={v}" for k, v in sorted(alert.items())
                        if k not in ("t",) and not isinstance(v, (dict,
                                                                  list)))
        lines.append(f"alert  : {desc}")
    metric = (alert or {}).get("metric") if isinstance(alert, dict) \
        else None
    hist_doc = bundle.get("history") or {}
    buckets = hist_doc.get("buckets") or []
    if metric and buckets:
        # The alert metric may be a derived series name
        # (<hist>.p99_s / <stage>.gbps) — strip the suffix back to the
        # stored name.
        stored = re.sub(r"\.(p99_s|gbps)$", "", metric)
        vals = [p["value"] for p in
                (bucket_point(r, stored) for r in buckets) if p]
        if vals:
            lines.append(f"history: {stored} {sparkline(vals)} "
                         f"lo={min(vals):.6g} hi={max(vals):.6g}")
    events = incident_timeline(bundle, window)
    lines.append(f"timeline ({len(events)} event(s)):")
    for t, src, text in events:
        ts = time.strftime("%H:%M:%S", time.gmtime(t))
        lines.append(f"  {ts} [{src:<14}] {text}")
    if bundle.get("torn_lines"):
        lines.append(f"({bundle['torn_lines']} torn request line(s) "
                     "healed)")
    return "\n".join(lines)


# -- long-horizon SLO reports ------------------------------------------------


def slo_report(store: Optional[HistoryStore] = None, *,
               objectives: Optional[Iterable] = None,
               window_s: float = 86400.0,
               now: Optional[float] = None,
               buckets: Optional[List[Dict]] = None,
               config: SiteConfig = DEFAULT) -> Dict:
    """Attainment + error-budget spend per objective over a window,
    straight from stored buckets (``store`` or an explicit ``buckets``
    list — a door's merged fan-out works too).

    Per objective: the stored per-bucket ``burn`` observations sum
    (exact — they were measured per tick); buckets that predate the
    burn feed fall back to recomputing from the stored histogram
    state / stage rate, the same :func:`~blit.monitor.bad_fraction`
    cut the live evaluator uses.  ``attainment = 1 - bad/total``
    (1.0 over an empty window — no traffic spends no budget);
    ``budget_spent = (bad/total) / budget`` (1.0 = the whole error
    budget, the SRE burn integral).  The ``metrics`` block carries
    flat ``slo.<name>_attained`` keys so
    :func:`blit.monitor.bench_metrics` ingests the report unchanged
    and ``blit bench-diff`` gates attainment."""
    from blit.monitor import bad_fraction, objectives_for

    objs = list(objectives) if objectives is not None \
        else objectives_for(config)
    now = (store.clock() if store is not None else time.time()) \
        if now is None else now
    t0 = now - float(window_s)
    if buckets is None:
        buckets = store.buckets(t0, now) if store is not None else []
    if objectives is None:
        # The store outranks the reader's config: burn counts recorded
        # under an objective name this host doesn't declare (another
        # peer's config, a since-removed objective) still report —
        # bad/total sums need no threshold, only the name and budget.
        known = {getattr(o, "name", None) or o["name"] for o in objs}
        recorded = sorted({name for rec in buckets
                           for name in (rec.get("burn") or {})
                           if name not in known})
        for name in recorded:
            objs.append({"name": name, "metric": name, "kind": "burn",
                         "threshold": 0.0, "budget": config.slo_budget})
    out_objs: Dict[str, Dict] = {}
    metrics: Dict[str, float] = {}
    for o in objs:
        name = getattr(o, "name", None) or o["name"]
        kind = getattr(o, "kind", None) or o.get("kind", "latency")
        metric = getattr(o, "metric", None) or o["metric"]
        threshold = float(getattr(o, "threshold", None)
                          if hasattr(o, "threshold") else o["threshold"])
        budget = float(getattr(o, "budget", None)
                       if hasattr(o, "budget") else o.get("budget", 0.01))
        bad = total = 0
        worst: Optional[Dict] = None
        for rec in buckets:
            b = (rec.get("burn") or {}).get(name)
            if b is not None:
                rb, rt = int(b.get("bad", 0)), int(b.get("total", 0))
            elif kind == "latency":
                hs = (rec.get("hists") or {}).get(metric)
                if hs is None:
                    continue
                h = HistogramStats.from_state(hs)
                rb, rt = bad_fraction(h, threshold)
            else:
                st = (rec.get("stages") or {}).get(metric)
                if st is None or float(st.get("seconds", 0.0)) <= 0:
                    continue
                gbps = (int(st.get("bytes", 0))
                        / float(st["seconds"]) / 1e9)
                rb, rt = (1, 1) if gbps < threshold else (0, 1)
            bad += rb
            total += rt
            if rt and (worst is None
                       or rb / rt > worst["bad"] / max(1, worst["total"])):
                worst = {"t0": rec.get("t0"), "bad": rb, "total": rt}
        frac = bad / total if total else 0.0
        attainment = 1.0 - frac
        out_objs[name] = {
            "kind": kind, "metric": metric, "threshold": threshold,
            "budget": budget, "bad": bad, "total": total,
            "attainment": round(attainment, 6),
            "budget_spent": round(frac / budget, 4),
            "worst_bucket": worst,
        }
        metrics[f"slo.{name}_attained"] = round(attainment, 6)
    return {"t0": t0, "t1": now, "window_s": float(window_s),
            "buckets": len(buckets), "objectives": out_objs,
            "metrics": metrics}


def render_slo_report(doc: Dict) -> str:
    """``blit slo-report``'s human table."""
    days = doc.get("window_s", 0.0) / 86400.0
    lines = [f"slo-report over {days:.2g} day(s) "
             f"({doc.get('buckets', 0)} bucket(s))"]
    lines.append(f"{'objective':<24} {'attainment':>11} {'budget%':>9} "
                 f"{'bad':>8} {'total':>10} worst bucket")
    for name, o in sorted((doc.get("objectives") or {}).items()):
        worst = o.get("worst_bucket")
        wtxt = "-"
        if worst and worst.get("total"):
            wt = time.strftime("%m-%d %H:%M",
                               time.gmtime(worst.get("t0", 0.0)))
            wtxt = f"{wt} ({worst['bad']}/{worst['total']})"
        lines.append(
            f"{name:<24} {o.get('attainment', 0.0):>11.6f} "
            f"{o.get('budget_spent', 0.0) * 100:>8.1f}% "
            f"{o.get('bad', 0):>8} {o.get('total', 0):>10} {wtxt}")
    if not doc.get("objectives"):
        lines.append("(no objectives configured — set BLIT_SLO_* or "
                     "SiteConfig.slo_*)")
    return "\n".join(lines)


# -- sparklines / `blit top --history` ---------------------------------------

_SPARK = "▁▂▃▄▅▆▇█"


def sparkline(values: List[float], width: int = 32) -> str:
    """A min–max-normalized unicode sparkline of the LAST ``width``
    values (flat series render as a low bar, not noise)."""
    vals = [float(v) for v in values][-max(1, int(width)):]
    if not vals:
        return ""
    lo, hi = min(vals), max(vals)
    if hi - lo <= 0:
        return _SPARK[0] * len(vals)
    idx = [int((v - lo) / (hi - lo) * (len(_SPARK) - 1)) for v in vals]
    return "".join(_SPARK[i] for i in idx)


def render_history_panel(store: HistoryStore,
                         metrics: Optional[List[str]] = None, *,
                         buckets: int = 32, max_rows: int = 12,
                         now: Optional[float] = None) -> str:
    """The ``blit top --history`` panel: one sparkline row per metric
    over the store's last ``buckets`` finest-tier buckets."""
    now = store.clock() if now is None else now
    rings = store._ring_headers()
    if not rings:
        return "history: (no store)"
    rings.sort(key=lambda ph: float(ph[1]["bucket_s"]))
    bucket_s = float(rings[0][1]["bucket_s"])
    tier = str(rings[0][1]["tier"])
    t0 = now - buckets * bucket_s
    names = metrics if metrics else store.metrics(
        window_s=buckets * bucket_s)[:max_rows]
    lines = [f"history ({tier} tier, {bucket_s:g}s buckets, "
             f"last {buckets})"]
    for name in names:
        pts = store.series(name, t0, now, tier=tier)
        vals = [p["value"] for p in pts]
        if not vals:
            continue
        lines.append(f"  {name:<28} {sparkline(vals, buckets):<{buckets}} "
                     f"lo={min(vals):.4g} hi={max(vals):.4g} "
                     f"now={vals[-1]:.4g}")
    if len(lines) == 1:
        lines.append("  (no series in window)")
    return "\n".join(lines)
