"""Version-bridging JAX shims.

The collective data plane targets current JAX (``jax.shard_map`` with
``check_vma``), but deployment rigs pin older releases where the API
still lives at ``jax.experimental.shard_map.shard_map`` with the
``check_rep`` spelling.  Every blit call site goes through this one
bridge so the version split lives in exactly one place.
"""

from __future__ import annotations


def shard_map(f, *, mesh, in_specs, out_specs, check_vma: bool = True):
    """``jax.shard_map`` across JAX versions (keyword form only — the
    blit call convention).  ``check_vma`` maps onto the old API's
    ``check_rep`` (same meaning: static per-axis invariance checking,
    disabled where psum/all_gather outputs defeat the analysis)."""
    import jax

    if hasattr(jax, "shard_map"):
        return jax.shard_map(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
            check_vma=check_vma,
        )
    from jax.experimental.shard_map import shard_map as _shard_map

    return _shard_map(
        f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
        check_rep=check_vma,
    )
