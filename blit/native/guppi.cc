// Threaded GUPPI RAW block reader — the C++ rebuild of Blio.jl's native-side
// role (SURVEY.md §2.3: "GUPPI RAW block reader ... for the GB/s host→device
// feed").  Python's single-threaded read path caps well below NVMe/pagecache
// bandwidth; this reader fans pread() calls across threads so a voltage
// block lands in the destination buffer at storage speed.
//
// Exposed C ABI (ctypes-consumed by blit/io/native.py):
//   blit_guppi_pread(path, offset, size, out, nthreads) -> 0 | errno-like <0

#include <fcntl.h>
#include <unistd.h>
#include <cerrno>
#include <cstdint>
#include <cstring>
#include <thread>
#include <vector>

namespace {

// One worker: pread [off, off+len) into dst.
int pread_range(int fd, uint8_t* dst, uint64_t off, uint64_t len) {
  while (len > 0) {
    ssize_t r = ::pread(fd, dst, len, (off_t)off);
    if (r < 0) {
      if (errno == EINTR) continue;
      return -errno;
    }
    if (r == 0) return -EIO;  // unexpected EOF
    dst += r;
    off += (uint64_t)r;
    len -= (uint64_t)r;
  }
  return 0;
}

}  // namespace

extern "C" {

// Strided per-channel read: GUPPI blocks are channel-major on disk
// ([chan][ntime][pol][2]), and the streaming pipeline appends each block at
// a time offset inside a persistent (chan, cap, pol, 2) ring buffer — so
// the destination rows are contiguous but strided per channel.  Reading
// channel c's bytes [offset + c*src_stride, +chan_bytes) straight into
// out + c*dst_stride lands the block in the ring with ZERO intermediate
// copies (the drop-overlap trim and time-skip fall out of chan_bytes /
// offset arithmetic).  Channels fan out round-robin across threads.
int blit_guppi_pread2(const char* path, uint64_t offset, uint64_t nchan,
                      uint64_t chan_bytes, uint64_t src_stride,
                      uint64_t dst_stride, void* out, int nthreads) {
  int fd = ::open(path, O_RDONLY);
  if (fd < 0) return -errno;
  if (nthreads < 1) nthreads = 1;
  const uint64_t kMinPerThread = 4ull << 20;
  uint64_t total = nchan * chan_bytes;
  uint64_t want = (total + kMinPerThread - 1) / kMinPerThread;
  if ((uint64_t)nthreads > want) nthreads = (int)want;
  if ((uint64_t)nthreads > nchan) nthreads = (int)nchan;
  if (nthreads <= 1) {
    int rc = 0;
    for (uint64_t c = 0; c < nchan && rc == 0; c++) {
      rc = pread_range(fd, (uint8_t*)out + c * dst_stride,
                       offset + c * src_stride, chan_bytes);
    }
    ::close(fd);
    return rc;
  }
  std::vector<std::thread> threads;
  std::vector<int> rcs(nthreads, 0);
  for (int t = 0; t < nthreads; t++) {
    threads.emplace_back([=, &rcs] {
      for (uint64_t c = (uint64_t)t; c < nchan; c += (uint64_t)nthreads) {
        int rc = pread_range(fd, (uint8_t*)out + c * dst_stride,
                             offset + c * src_stride, chan_bytes);
        if (rc) {
          rcs[t] = rc;
          return;
        }
      }
    });
  }
  for (auto& th : threads) th.join();
  ::close(fd);
  for (int rc : rcs)
    if (rc) return rc;
  return 0;
}

int blit_guppi_pread(const char* path, uint64_t offset, uint64_t size,
                     void* out, int nthreads) {
  int fd = ::open(path, O_RDONLY);
  if (fd < 0) return -errno;
  if (nthreads < 1) nthreads = 1;
  // Don't spawn threads for small reads (syscall + join overhead).
  const uint64_t kMinPerThread = 4ull << 20;
  uint64_t want = (size + kMinPerThread - 1) / kMinPerThread;
  if ((uint64_t)nthreads > want) nthreads = (int)want;
  if (nthreads <= 1) {
    int rc = pread_range(fd, (uint8_t*)out, offset, size);
    ::close(fd);
    return rc;
  }
  std::vector<std::thread> threads;
  std::vector<int> rcs(nthreads, 0);
  uint64_t chunk = size / nthreads;
  for (int t = 0; t < nthreads; t++) {
    uint64_t off = offset + (uint64_t)t * chunk;
    uint64_t len = (t == nthreads - 1) ? size - (uint64_t)t * chunk : chunk;
    uint8_t* dst = (uint8_t*)out + (uint64_t)t * chunk;
    threads.emplace_back([fd, dst, off, len, t, &rcs] {
      rcs[t] = pread_range(fd, dst, off, len);
    });
  }
  for (auto& th : threads) th.join();
  ::close(fd);
  for (int rc : rcs)
    if (rc) return rc;
  return 0;
}

}  // extern "C"
