// Threaded GUPPI RAW block reader — the C++ rebuild of Blio.jl's native-side
// role (SURVEY.md §2.3: "GUPPI RAW block reader ... for the GB/s host→device
// feed").  Python's single-threaded read path caps well below NVMe/pagecache
// bandwidth; this reader fans pread() calls across threads so a voltage
// block lands in the destination buffer at storage speed.
//
// Exposed C ABI (ctypes-consumed by blit/io/native.py):
//   blit_guppi_pread(path, offset, size, out, nthreads) -> 0 | errno-like <0

#include <fcntl.h>
#include <unistd.h>
#include <cerrno>
#include <cstdint>
#include <cstring>
#include <thread>
#include <vector>

namespace {

// One worker: pread [off, off+len) into dst.
int pread_range(int fd, uint8_t* dst, uint64_t off, uint64_t len) {
  while (len > 0) {
    ssize_t r = ::pread(fd, dst, len, (off_t)off);
    if (r < 0) {
      if (errno == EINTR) continue;
      return -errno;
    }
    if (r == 0) return -EIO;  // unexpected EOF
    dst += r;
    off += (uint64_t)r;
    len -= (uint64_t)r;
  }
  return 0;
}

}  // namespace

extern "C" {

int blit_guppi_pread(const char* path, uint64_t offset, uint64_t size,
                     void* out, int nthreads) {
  int fd = ::open(path, O_RDONLY);
  if (fd < 0) return -errno;
  if (nthreads < 1) nthreads = 1;
  // Don't spawn threads for small reads (syscall + join overhead).
  const uint64_t kMinPerThread = 4ull << 20;
  uint64_t want = (size + kMinPerThread - 1) / kMinPerThread;
  if ((uint64_t)nthreads > want) nthreads = (int)want;
  if (nthreads <= 1) {
    int rc = pread_range(fd, (uint8_t*)out, offset, size);
    ::close(fd);
    return rc;
  }
  std::vector<std::thread> threads;
  std::vector<int> rcs(nthreads, 0);
  uint64_t chunk = size / nthreads;
  for (int t = 0; t < nthreads; t++) {
    uint64_t off = offset + (uint64_t)t * chunk;
    uint64_t len = (t == nthreads - 1) ? size - (uint64_t)t * chunk : chunk;
    uint8_t* dst = (uint8_t*)out + (uint64_t)t * chunk;
    threads.emplace_back([fd, dst, off, len, t, &rcs] {
      rcs[t] = pread_range(fd, dst, off, len);
    });
  }
  for (auto& th : threads) th.join();
  ::close(fd);
  for (int rc : rcs)
    if (rc) return rc;
  return 0;
}

}  // extern "C"
