// Bitshuffle + LZ4 codec for FBH5 chunks — the C++ rebuild of the
// reference's H5Zbitshuffle dependency (SURVEY.md §2.3: bitshuffle C library
// with SSE2/AVX2 kernels wrapped by H5Zbitshuffle.jl, Project.toml:9).
//
// Implements the bitshuffle on-disk format (HDF5 filter id 32008, LZ4 mode):
//
//   chunk payload := [u64 BE total uncompressed bytes]
//                    [u32 BE block size in bytes]
//                    repeat: [u32 BE compressed size][LZ4 block]
//                    [raw leftover: (nelem % 8) * elem_size bytes]
//
// Each block of `block_size` elements is bit-transposed ("bitshuffled") then
// LZ4-compressed independently.  The bit transpose layout: for a block of n
// elements of elem_size bytes, output row (byte_pos*8 + bit) (bit 0 = LSB)
// holds n/8 bytes; bit j of its byte i is bit `bit` of byte `byte_pos` of
// element 8i+j.  This matches upstream bitshuffle's
// trans_byte_elem → trans_bit_byte → trans_bitrow_eight pipeline.
//
// LZ4 block compression comes from the system liblz4 (stable C ABI,
// prototypes declared below — no headers shipped in this image).
//
// Exposed C ABI (ctypes-consumed by blit/io/bshuf.py):
//   blit_bshuf_shuffle / blit_bshuf_unshuffle    — bit transpose only
//   blit_bshuf_compress_lz4 / _decompress_lz4    — full chunk codec
//   blit_bshuf_compress_bound, blit_bshuf_default_block_size

#include <cstdint>
#include <cstring>
#include <cstddef>

extern "C" {
// liblz4.so.1 ABI (stable since lz4 r129).
int LZ4_compress_default(const char* src, char* dst, int srcSize, int dstCapacity);
int LZ4_decompress_safe(const char* src, char* dst, int compressedSize, int dstCapacity);
int LZ4_compressBound(int inputSize);
}

namespace {

constexpr size_t kBlockedMult = 8;      // elements per bit-transpose unit
constexpr size_t kTargetBlockBytes = 8192;
constexpr size_t kMinBlockElems = 128;

// 8x8 bit-matrix transpose on a little-endian u64 (Hacker's Delight 7-3).
inline void trans_bit_8x8(uint64_t& x) {
  uint64_t t;
  t = (x ^ (x >> 7)) & 0x00AA00AA00AA00AAULL;
  x = x ^ t ^ (t << 7);
  t = (x ^ (x >> 14)) & 0x0000CCCC0000CCCCULL;
  x = x ^ t ^ (t << 14);
  t = (x ^ (x >> 28)) & 0x00000000F0F0F0F0ULL;
  x = x ^ t ^ (t << 28);
}

// Bitshuffle one block: nelem must be a multiple of 8.
// in: nelem elements of elem_size bytes; out: same byte count.
void shuffle_block(const uint8_t* in, uint8_t* out, size_t nelem,
                   size_t elem_size) {
  const size_t nrow_bytes = nelem / 8;  // bytes per bit plane
  for (size_t b = 0; b < elem_size; b++) {
    for (size_t i = 0; i < nrow_bytes; i++) {
      // Gather byte `b` of elements 8i..8i+7 into a u64 (byte j = elem 8i+j).
      uint64_t x = 0;
      for (size_t j = 0; j < 8; j++) {
        x |= (uint64_t)in[(8 * i + j) * elem_size + b] << (8 * j);
      }
      trans_bit_8x8(x);
      // After transpose, byte k of x = bit k of the 8 gathered bytes.
      for (size_t k = 0; k < 8; k++) {
        out[(b * 8 + k) * nrow_bytes + i] = (uint8_t)(x >> (8 * k));
      }
    }
  }
}

void unshuffle_block(const uint8_t* in, uint8_t* out, size_t nelem,
                     size_t elem_size) {
  const size_t nrow_bytes = nelem / 8;
  for (size_t b = 0; b < elem_size; b++) {
    for (size_t i = 0; i < nrow_bytes; i++) {
      uint64_t x = 0;
      for (size_t k = 0; k < 8; k++) {
        x |= (uint64_t)in[(b * 8 + k) * nrow_bytes + i] << (8 * k);
      }
      trans_bit_8x8(x);  // involution: same transpose inverts
      for (size_t j = 0; j < 8; j++) {
        out[(8 * i + j) * elem_size + b] = (uint8_t)(x >> (8 * j));
      }
    }
  }
}

inline void store_be32(uint8_t* p, uint32_t v) {
  p[0] = v >> 24; p[1] = v >> 16; p[2] = v >> 8; p[3] = v;
}
inline void store_be64(uint8_t* p, uint64_t v) {
  for (int i = 0; i < 8; i++) p[i] = (uint8_t)(v >> (56 - 8 * i));
}
inline uint32_t load_be32(const uint8_t* p) {
  return ((uint32_t)p[0] << 24) | ((uint32_t)p[1] << 16) |
         ((uint32_t)p[2] << 8) | p[3];
}
inline uint64_t load_be64(const uint8_t* p) {
  uint64_t v = 0;
  for (int i = 0; i < 8; i++) v = (v << 8) | p[i];
  return v;
}

}  // namespace

extern "C" {

size_t blit_bshuf_default_block_size(size_t elem_size) {
  size_t bs = kTargetBlockBytes / elem_size;
  bs = (bs / kBlockedMult) * kBlockedMult;
  if (bs < kMinBlockElems) bs = kMinBlockElems;
  return bs;
}

// Bit transpose only (no compression); nelem must be a multiple of 8.
// Returns 0 on success.
int blit_bshuf_shuffle(const void* in, void* out, size_t nelem,
                       size_t elem_size) {
  if (nelem % 8) return -1;
  shuffle_block((const uint8_t*)in, (uint8_t*)out, nelem, elem_size);
  return 0;
}

int blit_bshuf_unshuffle(const void* in, void* out, size_t nelem,
                         size_t elem_size) {
  if (nelem % 8) return -1;
  unshuffle_block((const uint8_t*)in, (uint8_t*)out, nelem, elem_size);
  return 0;
}

int64_t blit_bshuf_compress_bound(size_t nelem, size_t elem_size,
                                  size_t block_elems) {
  if (block_elems == 0) block_elems = blit_bshuf_default_block_size(elem_size);
  size_t nblocks = nelem / block_elems + 2;  // + partial + slack
  size_t block_bytes = block_elems * elem_size;
  return 12 + (int64_t)nblocks * (4 + LZ4_compressBound((int)block_bytes)) +
         8 * elem_size;
}

// Compress nelem elements into the bitshuffle-LZ4 HDF5 chunk format.
// block_elems == 0 -> default.  Returns bytes written, or < 0 on error.
int64_t blit_bshuf_compress_lz4(const void* in_v, void* out_v, size_t nelem,
                                size_t elem_size, size_t block_elems) {
  const uint8_t* in = (const uint8_t*)in_v;
  uint8_t* out = (uint8_t*)out_v;
  if (block_elems == 0) block_elems = blit_bshuf_default_block_size(elem_size);
  if (block_elems % kBlockedMult) return -2;
  const size_t block_bytes = block_elems * elem_size;

  uint8_t* p = out;
  store_be64(p, (uint64_t)nelem * elem_size); p += 8;
  store_be32(p, (uint32_t)block_bytes); p += 4;

  // Scratch for one shuffled block.
  uint8_t* tmp = new uint8_t[block_bytes];
  size_t done = 0;
  while (done + block_elems <= nelem) {
    shuffle_block(in + done * elem_size, tmp, block_elems, elem_size);
    int c = LZ4_compress_default((const char*)tmp, (char*)(p + 4),
                                 (int)block_bytes,
                                 LZ4_compressBound((int)block_bytes));
    if (c <= 0) { delete[] tmp; return -3; }
    store_be32(p, (uint32_t)c);
    p += 4 + c;
    done += block_elems;
  }
  // Final partial block, rounded down to a multiple of 8 elements.
  size_t rem = nelem - done;
  size_t last = rem - rem % kBlockedMult;
  if (last) {
    size_t last_bytes = last * elem_size;
    shuffle_block(in + done * elem_size, tmp, last, elem_size);
    int c = LZ4_compress_default((const char*)tmp, (char*)(p + 4),
                                 (int)last_bytes,
                                 LZ4_compressBound((int)last_bytes));
    if (c <= 0) { delete[] tmp; return -3; }
    store_be32(p, (uint32_t)c);
    p += 4 + c;
    done += last;
  }
  delete[] tmp;
  // Sub-8-element leftover: raw copy, no framing.
  size_t left_bytes = (nelem - done) * elem_size;
  if (left_bytes) {
    std::memcpy(p, in + done * elem_size, left_bytes);
    p += left_bytes;
  }
  return (int64_t)(p - out);
}

// Decompress a bitshuffle-LZ4 chunk.  out must hold nelem*elem_size bytes.
// Returns bytes consumed from `in`, or < 0 on error.
int64_t blit_bshuf_decompress_lz4(const void* in_v, size_t in_size,
                                  void* out_v, size_t nelem,
                                  size_t elem_size) {
  const uint8_t* in = (const uint8_t*)in_v;
  uint8_t* out = (uint8_t*)out_v;
  if (in_size < 12) return -1;
  const uint64_t total = load_be64(in);
  if (total != (uint64_t)nelem * elem_size) return -4;
  const size_t block_bytes = load_be32(in + 8);
  if (block_bytes == 0 || block_bytes % (kBlockedMult * elem_size)) return -2;
  const size_t block_elems = block_bytes / elem_size;
  const uint8_t* p = in + 12;
  const uint8_t* end = in + in_size;

  uint8_t* tmp = new uint8_t[block_bytes];
  size_t done = 0;
  while (done < nelem - nelem % kBlockedMult) {
    size_t this_elems = block_elems;
    if (done + this_elems > nelem) this_elems = (nelem - done) - (nelem - done) % kBlockedMult;
    if (this_elems == 0) break;
    size_t this_bytes = this_elems * elem_size;
    if (p + 4 > end) { delete[] tmp; return -1; }
    uint32_t c = load_be32(p); p += 4;
    if (p + c > end) { delete[] tmp; return -1; }
    int d = LZ4_decompress_safe((const char*)p, (char*)tmp, (int)c,
                                (int)this_bytes);
    if (d != (int)this_bytes) { delete[] tmp; return -3; }
    unshuffle_block(tmp, out + done * elem_size, this_elems, elem_size);
    p += c;
    done += this_elems;
  }
  delete[] tmp;
  size_t left_bytes = (nelem - done) * elem_size;
  if (left_bytes) {
    if (p + left_bytes > end) return -1;
    std::memcpy(out + done * elem_size, p, left_bytes);
    p += left_bytes;
  }
  return (int64_t)(p - in);
}

}  // extern "C"
