// Bitshuffle + LZ4 codec for FBH5 chunks — the C++ rebuild of the
// reference's H5Zbitshuffle dependency (SURVEY.md §2.3: bitshuffle C library
// with SSE2/AVX2 kernels wrapped by H5Zbitshuffle.jl, Project.toml:9).
//
// Implements the bitshuffle on-disk format (HDF5 filter id 32008, LZ4 mode):
//
//   chunk payload := [u64 BE total uncompressed bytes]
//                    [u32 BE block size in bytes]
//                    repeat: [u32 BE compressed size][LZ4 block]
//                    [raw leftover: (nelem % 8) * elem_size bytes]
//
// Each block of `block_size` elements is bit-transposed ("bitshuffled") then
// LZ4-compressed independently.  The bit transpose layout: for a block of n
// elements of elem_size bytes, output row (byte_pos*8 + bit) (bit 0 = LSB)
// holds n/8 bytes; bit j of its byte i is bit `bit` of byte `byte_pos` of
// element 8i+j.  This matches upstream bitshuffle's
// trans_byte_elem → trans_bit_byte → trans_bitrow_eight pipeline.
//
// LZ4 block compression comes from the system liblz4 (stable C ABI,
// prototypes declared below — no headers shipped in this image).
//
// Exposed C ABI (ctypes-consumed by blit/io/bshuf.py):
//   blit_bshuf_shuffle / blit_bshuf_unshuffle    — bit transpose only
//   blit_bshuf_compress_lz4 / _decompress_lz4    — full chunk codec
//   blit_bshuf_compress_bound, blit_bshuf_default_block_size

#include <cstdint>
#include <cstring>
#include <cstddef>

#if defined(__AVX2__)
#include <immintrin.h>
#endif

extern "C" {
// liblz4.so.1 ABI (stable since lz4 r129).
int LZ4_compress_default(const char* src, char* dst, int srcSize, int dstCapacity);
int LZ4_decompress_safe(const char* src, char* dst, int compressedSize, int dstCapacity);
int LZ4_compressBound(int inputSize);
}

namespace {

constexpr size_t kBlockedMult = 8;      // elements per bit-transpose unit
constexpr size_t kTargetBlockBytes = 8192;
constexpr size_t kMinBlockElems = 128;

// 8x8 bit-matrix transpose on a little-endian u64 (Hacker's Delight 7-3).
inline void trans_bit_8x8(uint64_t& x) {
  uint64_t t;
  t = (x ^ (x >> 7)) & 0x00AA00AA00AA00AAULL;
  x = x ^ t ^ (t << 7);
  t = (x ^ (x >> 14)) & 0x0000CCCC0000CCCCULL;
  x = x ^ t ^ (t << 14);
  t = (x ^ (x >> 28)) & 0x00000000F0F0F0F0ULL;
  x = x ^ t ^ (t << 28);
}

// 8x8 BYTE-matrix transpose on 8 little-endian u64 rows: afterwards byte k
// of r[j] = byte j of the original r[k].  Three levels of block swaps, all
// word-wide — the building block that lets shuffle/unshuffle run on u64
// loads/stores instead of byte-granular strided gathers (the scalar
// reference path below was measured at ~0.1 GB/s/core; this restructure is
// worth ~5x, upstream bitshuffle's SSE2/AVX2 kernels being the model).
inline void trans_byte_8x8(uint64_t r[8]) {
  uint64_t t;
  for (int i = 0; i < 8; i += 2) {
    t = ((r[i] >> 8) ^ r[i + 1]) & 0x00FF00FF00FF00FFULL;
    r[i] ^= t << 8;
    r[i + 1] ^= t;
  }
  for (int i = 0; i < 8; i += 4) {
    for (int j = 0; j < 2; j++) {
      t = ((r[i + j] >> 16) ^ r[i + j + 2]) & 0x0000FFFF0000FFFFULL;
      r[i + j] ^= t << 16;
      r[i + j + 2] ^= t;
    }
  }
  for (int j = 0; j < 4; j++) {
    t = ((r[j] >> 32) ^ r[j + 4]) & 0x00000000FFFFFFFFULL;
    r[j] ^= t << 32;
    r[j + 4] ^= t;
  }
}

// Scalar reference paths (tail handling + elem_size > 8).
void shuffle_scalar(const uint8_t* in, uint8_t* out, size_t nelem,
                    size_t elem_size, size_t nrow_bytes, size_t i0,
                    size_t i1) {
  (void)nelem;
  for (size_t b = 0; b < elem_size; b++) {
    for (size_t i = i0; i < i1; i++) {
      // Gather byte `b` of elements 8i..8i+7 into a u64 (byte j = elem 8i+j).
      uint64_t x = 0;
      for (size_t j = 0; j < 8; j++) {
        x |= (uint64_t)in[(8 * i + j) * elem_size + b] << (8 * j);
      }
      trans_bit_8x8(x);
      // After transpose, byte k of x = bit k of the 8 gathered bytes.
      for (size_t k = 0; k < 8; k++) {
        out[(b * 8 + k) * nrow_bytes + i] = (uint8_t)(x >> (8 * k));
      }
    }
  }
}

void unshuffle_scalar(const uint8_t* in, uint8_t* out, size_t nelem,
                      size_t elem_size, size_t nrow_bytes, size_t i0,
                      size_t i1) {
  (void)nelem;
  for (size_t b = 0; b < elem_size; b++) {
    for (size_t i = i0; i < i1; i++) {
      uint64_t x = 0;
      for (size_t k = 0; k < 8; k++) {
        x |= (uint64_t)in[(b * 8 + k) * nrow_bytes + i] << (8 * k);
      }
      trans_bit_8x8(x);  // involution: same transpose inverts
      for (size_t j = 0; j < 8; j++) {
        out[(8 * i + j) * elem_size + b] = (uint8_t)(x >> (8 * j));
      }
    }
  }
}

#if defined(__AVX2__)

// ---- AVX2 fast paths (elem_size 1/2/4; upstream bitshuffle's SSE2/AVX2
// kernels are the model).  Elements stream through a small L1-resident
// SoA staging buffer: byte planes are (de)interleaved with SSE unpack
// pyramids, bit planes with vpmovmskb (shuffle) / a shuffle_epi8+cmpeq
// bit-expand (unshuffle) — ~1.5 instructions per byte instead of the u64
// path's ~3 word ops per 8 bytes.

constexpr size_t kChunkElems = 512;  // SoA staging chunk; 8 planes = 4 KB

// Expand the 32 bits of `w` into 32 bytes: byte e = 0xFF iff bit e set.
inline __m256i expand_bits_32(uint32_t w) {
  __m256i v = _mm256_set1_epi32((int)w);
  // shuffle_epi8 is lane-local; the word is replicated in both lanes, so
  // lane-local source bytes 0..3 are the word's bytes in each lane.
  const __m256i sel = _mm256_setr_epi8(
      0, 0, 0, 0, 0, 0, 0, 0, 1, 1, 1, 1, 1, 1, 1, 1,
      2, 2, 2, 2, 2, 2, 2, 2, 3, 3, 3, 3, 3, 3, 3, 3);
  v = _mm256_shuffle_epi8(v, sel);
  const __m256i bits = _mm256_set1_epi64x((long long)0x8040201008040201ULL);
  v = _mm256_and_si256(v, bits);
  return _mm256_cmpeq_epi8(v, bits);
}

// SoA byte planes -> interleaved elements (16-byte SSE unpack pyramid).
void interleave_soa(const uint8_t soa[8][kChunkElems], uint8_t* out,
                    size_t n, size_t es) {
  if (es == 1) {
    std::memcpy(out, soa[0], n);
    return;
  }
  if (es == 2) {
    for (size_t c = 0; c < n; c += 16) {
      __m128i a = _mm_loadu_si128((const __m128i*)(soa[0] + c));
      __m128i b = _mm_loadu_si128((const __m128i*)(soa[1] + c));
      _mm_storeu_si128((__m128i*)(out + 2 * c),
                       _mm_unpacklo_epi8(a, b));
      _mm_storeu_si128((__m128i*)(out + 2 * c + 16),
                       _mm_unpackhi_epi8(a, b));
    }
    return;
  }
  // es == 4
  for (size_t c = 0; c < n; c += 16) {
    __m128i a = _mm_loadu_si128((const __m128i*)(soa[0] + c));
    __m128i b = _mm_loadu_si128((const __m128i*)(soa[1] + c));
    __m128i cc = _mm_loadu_si128((const __m128i*)(soa[2] + c));
    __m128i d = _mm_loadu_si128((const __m128i*)(soa[3] + c));
    __m128i ab_lo = _mm_unpacklo_epi8(a, b);
    __m128i ab_hi = _mm_unpackhi_epi8(a, b);
    __m128i cd_lo = _mm_unpacklo_epi8(cc, d);
    __m128i cd_hi = _mm_unpackhi_epi8(cc, d);
    uint8_t* o = out + 4 * c;
    _mm_storeu_si128((__m128i*)(o), _mm_unpacklo_epi16(ab_lo, cd_lo));
    _mm_storeu_si128((__m128i*)(o + 16), _mm_unpackhi_epi16(ab_lo, cd_lo));
    _mm_storeu_si128((__m128i*)(o + 32), _mm_unpacklo_epi16(ab_hi, cd_hi));
    _mm_storeu_si128((__m128i*)(o + 48), _mm_unpackhi_epi16(ab_hi, cd_hi));
  }
}

// Interleaved elements -> SoA byte planes (stride-gather shuffles).
void deinterleave_aos(const uint8_t* in, uint8_t soa[8][kChunkElems],
                      size_t n, size_t es) {
  if (es == 1) {
    std::memcpy(soa[0], in, n);
    return;
  }
  if (es == 2) {
    const __m128i sel = _mm_setr_epi8(0, 2, 4, 6, 8, 10, 12, 14,
                                      1, 3, 5, 7, 9, 11, 13, 15);
    for (size_t c = 0; c < n; c += 16) {
      __m128i x0 = _mm_shuffle_epi8(
          _mm_loadu_si128((const __m128i*)(in + 2 * c)), sel);
      __m128i x1 = _mm_shuffle_epi8(
          _mm_loadu_si128((const __m128i*)(in + 2 * c + 16)), sel);
      _mm_storeu_si128((__m128i*)(soa[0] + c),
                       _mm_unpacklo_epi64(x0, x1));
      _mm_storeu_si128((__m128i*)(soa[1] + c),
                       _mm_unpackhi_epi64(x0, x1));
    }
    return;
  }
  // es == 4
  const __m128i sel = _mm_setr_epi8(0, 4, 8, 12, 1, 5, 9, 13,
                                    2, 6, 10, 14, 3, 7, 11, 15);
  for (size_t c = 0; c < n; c += 16) {
    const uint8_t* p = in + 4 * c;
    __m128i x0 = _mm_shuffle_epi8(_mm_loadu_si128((const __m128i*)p), sel);
    __m128i x1 = _mm_shuffle_epi8(
        _mm_loadu_si128((const __m128i*)(p + 16)), sel);
    __m128i x2 = _mm_shuffle_epi8(
        _mm_loadu_si128((const __m128i*)(p + 32)), sel);
    __m128i x3 = _mm_shuffle_epi8(
        _mm_loadu_si128((const __m128i*)(p + 48)), sel);
    __m128i t0 = _mm_unpacklo_epi32(x0, x1);
    __m128i t1 = _mm_unpackhi_epi32(x0, x1);
    __m128i t2 = _mm_unpacklo_epi32(x2, x3);
    __m128i t3 = _mm_unpackhi_epi32(x2, x3);
    _mm_storeu_si128((__m128i*)(soa[0] + c), _mm_unpacklo_epi64(t0, t2));
    _mm_storeu_si128((__m128i*)(soa[1] + c), _mm_unpackhi_epi64(t0, t2));
    _mm_storeu_si128((__m128i*)(soa[2] + c), _mm_unpacklo_epi64(t1, t3));
    _mm_storeu_si128((__m128i*)(soa[3] + c), _mm_unpackhi_epi64(t1, t3));
  }
}

void shuffle_avx2(const uint8_t* in, uint8_t* out, size_t nelem,
                  size_t elem_size) {
  const size_t nrow_bytes = nelem / 8;
  alignas(32) uint8_t soa[8][kChunkElems];
  size_t e0 = 0;
  for (; e0 + kChunkElems <= nelem; e0 += kChunkElems) {
    deinterleave_aos(in + e0 * elem_size, soa, kChunkElems, elem_size);
    for (size_t b = 0; b < elem_size; b++) {
      for (size_t c = 0; c < kChunkElems; c += 32) {
        __m256i x = _mm256_loadu_si256((const __m256i*)(soa[b] + c));
        for (size_t k = 8; k-- > 0;) {
          // vpmovmskb takes each byte's MSB: after 7-k doublings the MSB
          // is original bit k; bit j of the mask = element (c+j).
          uint32_t w = (uint32_t)_mm256_movemask_epi8(x);
          std::memcpy(out + (b * 8 + k) * nrow_bytes + e0 / 8 + c / 8,
                      &w, 4);
          x = _mm256_add_epi8(x, x);
        }
      }
    }
  }
  if (e0 < nelem) {
    shuffle_scalar(in, out, nelem, elem_size, nrow_bytes, e0 / 8,
                   nrow_bytes);
  }
}

void unshuffle_avx2(const uint8_t* in, uint8_t* out, size_t nelem,
                    size_t elem_size) {
  const size_t nrow_bytes = nelem / 8;
  alignas(32) uint8_t soa[8][kChunkElems];
  size_t e0 = 0;
  for (; e0 + kChunkElems <= nelem; e0 += kChunkElems) {
    for (size_t b = 0; b < elem_size; b++) {
      for (size_t c = 0; c < kChunkElems; c += 32) {
        __m256i acc = _mm256_setzero_si256();
        for (size_t k = 0; k < 8; k++) {
          uint32_t w;
          std::memcpy(&w, in + (b * 8 + k) * nrow_bytes + e0 / 8 + c / 8,
                      4);
          __m256i m = expand_bits_32(w);
          acc = _mm256_or_si256(
              acc,
              _mm256_and_si256(m, _mm256_set1_epi8((char)(1u << k))));
        }
        _mm256_storeu_si256((__m256i*)(soa[b] + c), acc);
      }
    }
    interleave_soa(soa, out + e0 * elem_size, kChunkElems, elem_size);
  }
  if (e0 < nelem) {
    unshuffle_scalar(in, out, nelem, elem_size, nrow_bytes, e0 / 8,
                     nrow_bytes);
  }
}

#endif  // __AVX2__

// Bitshuffle one block: nelem must be a multiple of 8.
// in: nelem elements of elem_size bytes; out: same byte count.
//
// Fast path (elem_size <= 8): process 8 bit-plane positions (64 elements)
// per step.  Element bytes are gathered with whole-u64 loads + an 8x8 byte
// transpose, bits with the 8x8 bit transpose, and rows stored as u64s —
// no byte-granular strided access anywhere.
void shuffle_block(const uint8_t* in, uint8_t* out, size_t nelem,
                   size_t elem_size) {
  const size_t nrow_bytes = nelem / 8;  // bytes per bit plane
#if defined(__AVX2__)
  if ((elem_size == 1 || elem_size == 2 || elem_size == 4) &&
      nelem >= kChunkElems) {
    shuffle_avx2(in, out, nelem, elem_size);
    return;
  }
#endif
  if (elem_size > 8 || nrow_bytes < 8) {
    shuffle_scalar(in, out, nelem, elem_size, nrow_bytes, 0, nrow_bytes);
    return;
  }
  const size_t i_fast = nrow_bytes & ~(size_t)7;
  uint64_t vals[8][8];  // [b][i'] — bit-transposed gathers per byte pos
  for (size_t i = 0; i < i_fast; i += 8) {
    for (size_t ip = 0; ip < 8; ip++) {
      // c[j] = the elem_size bytes of element 8(i+ip)+j.
      uint64_t c[8] = {0, 0, 0, 0, 0, 0, 0, 0};
      const uint8_t* src = in + 8 * (i + ip) * elem_size;
      for (size_t j = 0; j < 8; j++) {
        std::memcpy(&c[j], src + j * elem_size, elem_size);
      }
      trans_byte_8x8(c);  // c[b] byte j = byte b of element 8(i+ip)+j
      for (size_t b = 0; b < elem_size; b++) {
        uint64_t x = c[b];
        trans_bit_8x8(x);  // byte k = bit k of the 8 gathered bytes
        vals[b][ip] = x;
      }
    }
    for (size_t b = 0; b < elem_size; b++) {
      uint64_t r[8];
      for (size_t ip = 0; ip < 8; ip++) r[ip] = vals[b][ip];
      trans_byte_8x8(r);  // r[k] byte i' = row (b*8+k) byte (i+i')
      for (size_t k = 0; k < 8; k++) {
        std::memcpy(out + (b * 8 + k) * nrow_bytes + i, &r[k], 8);
      }
    }
  }
  if (i_fast < nrow_bytes) {
    shuffle_scalar(in, out, nelem, elem_size, nrow_bytes, i_fast,
                   nrow_bytes);
  }
}

void unshuffle_block(const uint8_t* in, uint8_t* out, size_t nelem,
                     size_t elem_size) {
  const size_t nrow_bytes = nelem / 8;
#if defined(__AVX2__)
  if ((elem_size == 1 || elem_size == 2 || elem_size == 4) &&
      nelem >= kChunkElems) {
    unshuffle_avx2(in, out, nelem, elem_size);
    return;
  }
#endif
  if (elem_size > 8 || nrow_bytes < 8) {
    unshuffle_scalar(in, out, nelem, elem_size, nrow_bytes, 0, nrow_bytes);
    return;
  }
  const size_t i_fast = nrow_bytes & ~(size_t)7;
  uint64_t vals[8][8];  // [b][i'] — byte b of elements 8(i+i')..+7
  for (size_t i = 0; i < i_fast; i += 8) {
    for (size_t b = 0; b < elem_size; b++) {
      uint64_t r[8];
      for (size_t k = 0; k < 8; k++) {
        std::memcpy(&r[k], in + (b * 8 + k) * nrow_bytes + i, 8);
      }
      trans_byte_8x8(r);  // r[i'] byte k = row (b*8+k) byte (i+i')
      for (size_t ip = 0; ip < 8; ip++) {
        uint64_t x = r[ip];
        trans_bit_8x8(x);  // byte j = out byte b of element 8(i+ip)+j
        vals[b][ip] = x;
      }
    }
    for (size_t ip = 0; ip < 8; ip++) {
      uint64_t c[8] = {0, 0, 0, 0, 0, 0, 0, 0};
      for (size_t b = 0; b < elem_size; b++) c[b] = vals[b][ip];
      trans_byte_8x8(c);  // c[j] byte b = out byte b of element 8(i+ip)+j
      uint8_t* dst = out + 8 * (i + ip) * elem_size;
      for (size_t j = 0; j < 8; j++) {
        std::memcpy(dst + j * elem_size, &c[j], elem_size);
      }
    }
  }
  if (i_fast < nrow_bytes) {
    unshuffle_scalar(in, out, nelem, elem_size, nrow_bytes, i_fast,
                     nrow_bytes);
  }
}

inline void store_be32(uint8_t* p, uint32_t v) {
  p[0] = v >> 24; p[1] = v >> 16; p[2] = v >> 8; p[3] = v;
}
inline void store_be64(uint8_t* p, uint64_t v) {
  for (int i = 0; i < 8; i++) p[i] = (uint8_t)(v >> (56 - 8 * i));
}
inline uint32_t load_be32(const uint8_t* p) {
  return ((uint32_t)p[0] << 24) | ((uint32_t)p[1] << 16) |
         ((uint32_t)p[2] << 8) | p[3];
}
inline uint64_t load_be64(const uint8_t* p) {
  uint64_t v = 0;
  for (int i = 0; i < 8; i++) v = (v << 8) | p[i];
  return v;
}

}  // namespace

extern "C" {

size_t blit_bshuf_default_block_size(size_t elem_size) {
  size_t bs = kTargetBlockBytes / elem_size;
  bs = (bs / kBlockedMult) * kBlockedMult;
  if (bs < kMinBlockElems) bs = kMinBlockElems;
  return bs;
}

// Bit transpose only (no compression); nelem must be a multiple of 8.
// Returns 0 on success.
int blit_bshuf_shuffle(const void* in, void* out, size_t nelem,
                       size_t elem_size) {
  if (nelem % 8) return -1;
  shuffle_block((const uint8_t*)in, (uint8_t*)out, nelem, elem_size);
  return 0;
}

int blit_bshuf_unshuffle(const void* in, void* out, size_t nelem,
                         size_t elem_size) {
  if (nelem % 8) return -1;
  unshuffle_block((const uint8_t*)in, (uint8_t*)out, nelem, elem_size);
  return 0;
}

int64_t blit_bshuf_compress_bound(size_t nelem, size_t elem_size,
                                  size_t block_elems) {
  if (block_elems == 0) block_elems = blit_bshuf_default_block_size(elem_size);
  size_t nblocks = nelem / block_elems + 2;  // + partial + slack
  size_t block_bytes = block_elems * elem_size;
  return 12 + (int64_t)nblocks * (4 + LZ4_compressBound((int)block_bytes)) +
         8 * elem_size;
}

// Compress nelem elements into the bitshuffle-LZ4 HDF5 chunk format.
// block_elems == 0 -> default.  Returns bytes written, or < 0 on error.
int64_t blit_bshuf_compress_lz4(const void* in_v, void* out_v, size_t nelem,
                                size_t elem_size, size_t block_elems) {
  const uint8_t* in = (const uint8_t*)in_v;
  uint8_t* out = (uint8_t*)out_v;
  if (block_elems == 0) block_elems = blit_bshuf_default_block_size(elem_size);
  if (block_elems % kBlockedMult) return -2;
  const size_t block_bytes = block_elems * elem_size;

  uint8_t* p = out;
  store_be64(p, (uint64_t)nelem * elem_size); p += 8;
  store_be32(p, (uint32_t)block_bytes); p += 4;

  // Scratch for one shuffled block.
  uint8_t* tmp = new uint8_t[block_bytes];
  size_t done = 0;
  while (done + block_elems <= nelem) {
    shuffle_block(in + done * elem_size, tmp, block_elems, elem_size);
    int c = LZ4_compress_default((const char*)tmp, (char*)(p + 4),
                                 (int)block_bytes,
                                 LZ4_compressBound((int)block_bytes));
    if (c <= 0) { delete[] tmp; return -3; }
    store_be32(p, (uint32_t)c);
    p += 4 + c;
    done += block_elems;
  }
  // Final partial block, rounded down to a multiple of 8 elements.
  size_t rem = nelem - done;
  size_t last = rem - rem % kBlockedMult;
  if (last) {
    size_t last_bytes = last * elem_size;
    shuffle_block(in + done * elem_size, tmp, last, elem_size);
    int c = LZ4_compress_default((const char*)tmp, (char*)(p + 4),
                                 (int)last_bytes,
                                 LZ4_compressBound((int)last_bytes));
    if (c <= 0) { delete[] tmp; return -3; }
    store_be32(p, (uint32_t)c);
    p += 4 + c;
    done += last;
  }
  delete[] tmp;
  // Sub-8-element leftover: raw copy, no framing.
  size_t left_bytes = (nelem - done) * elem_size;
  if (left_bytes) {
    std::memcpy(p, in + done * elem_size, left_bytes);
    p += left_bytes;
  }
  return (int64_t)(p - out);
}

// Decompress a bitshuffle-LZ4 chunk.  out must hold nelem*elem_size bytes.
// Returns bytes consumed from `in`, or < 0 on error.
int64_t blit_bshuf_decompress_lz4(const void* in_v, size_t in_size,
                                  void* out_v, size_t nelem,
                                  size_t elem_size) {
  const uint8_t* in = (const uint8_t*)in_v;
  uint8_t* out = (uint8_t*)out_v;
  if (in_size < 12) return -1;
  const uint64_t total = load_be64(in);
  if (total != (uint64_t)nelem * elem_size) return -4;
  const size_t block_bytes = load_be32(in + 8);
  if (block_bytes == 0 || block_bytes % (kBlockedMult * elem_size)) return -2;
  const size_t block_elems = block_bytes / elem_size;
  const uint8_t* p = in + 12;
  const uint8_t* end = in + in_size;

  uint8_t* tmp = new uint8_t[block_bytes];
  size_t done = 0;
  while (done < nelem - nelem % kBlockedMult) {
    size_t this_elems = block_elems;
    if (done + this_elems > nelem) this_elems = (nelem - done) - (nelem - done) % kBlockedMult;
    if (this_elems == 0) break;
    size_t this_bytes = this_elems * elem_size;
    if (p + 4 > end) { delete[] tmp; return -1; }
    uint32_t c = load_be32(p); p += 4;
    if (p + c > end) { delete[] tmp; return -1; }
    int d = LZ4_decompress_safe((const char*)p, (char*)tmp, (int)c,
                                (int)this_bytes);
    if (d != (int)this_bytes) { delete[] tmp; return -3; }
    unshuffle_block(tmp, out + done * elem_size, this_elems, elem_size);
    p += c;
    done += this_elems;
  }
  delete[] tmp;
  size_t left_bytes = (nelem - done) * elem_size;
  if (left_bytes) {
    if (p + left_bytes > end) return -1;
    std::memcpy(out + done * elem_size, p, left_bytes);
    p += left_bytes;
  }
  return (int64_t)(p - in);
}

}  // extern "C"
