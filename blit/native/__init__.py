"""Native C++ sources for blit's acceleration libraries (SURVEY.md §2.3).

This package carries no Python — it exists so the C++ sources, Makefile,
and built artifacts (``build/*.so``) travel with the installed package
(pyproject.toml package-data).  Build with ``make -C blit/native``;
loading happens in :mod:`blit.io.native`, which degrades to NumPy
fallbacks when the libraries are absent.
"""
