"""GUPPI / rawspec file-name and directory-name parsing.

Reference semantics: ``/root/reference/src/gbtworkerfunctions.jl:35-61`` (the
``parseguppiname`` / ``parserawspecname`` verbose regexes) and the session /
player directory regexes at ``src/gbt.jl:50-52``.

Two reference warts are deliberately *fixed* here (SURVEY.md §2.1):

- The reference player regex ``r"^BLP([?<band>0-7])(?<bank>[0-7])$"`` contains a
  malformed named group — the first "group" is really the character class
  ``[?<band>0-7]``, so junk like ``BLPd3`` is accepted.  The corrected regex
  ``^BLP(?P<band>[0-7])(?P<bank>[0-7])$`` is used.
- All dots in literal suffixes (``.rawspec.``, ``.h5``) are escaped.
"""

from __future__ import annotations

import re
from dataclasses import dataclass
from typing import Optional

# A session is a GBT project ID + session ID, e.g. "AGBT22B_999_01"
# (reference: src/gbt.jl:50, src/gbtworkerfunctions.jl:70).
SESSION_RE = re.compile(r"[AT]GBT[12][0-9][AB]_\d+_\d+")

# A player directory names the logical recording node "BLP<band><bank>"
# (reference: src/gbt.jl:52 — corrected; see module docstring).
PLAYER_RE = re.compile(r"^BLP(?P<band>[0-7])(?P<bank>[0-7])$")

# Default inventory file pattern: the low-resolution rawspec product
# (reference: src/gbt.jl:48).
DEFAULT_FILE_RE = re.compile(r"0002\.h5$")

# The /BLP<band><bank>/ path component, searched anywhere in the path.  The
# reference's single regex allows at most one intermediate path component
# between /BLPbb/ and the file (src/gbtworkerfunctions.jl:38), silently losing
# band/bank for deeper nesting; parsing the path component-wise removes that
# limitation while keeping band/bank semantics identical.
PLAYER_COMPONENT_RE = re.compile(r"/BLP(?P<band>[0-7])(?P<bank>[0-7])(?=/)")

# GUPPI-convention file basename, e.g.
#   blc42_guppi_59897_21221_HD_84406_0011.rawspec.0002.h5
# (reference: src/gbtworkerfunctions.jl:35-47).  Like Julia's `match`, this is
# searched (unanchored); the host prefix and the numeric field between smjd
# and source name are optional.
GUPPI_BASE_RE = re.compile(
    r"""
    (?:(?P<host>blc..)_)?
    guppi_
    (?P<imjd>\d+)_
    (?P<smjd>\d+)_
    (?:\d+_)?
    (?P<src>.*)_
    (?P<scan>\d{4})
    """,
    re.VERBOSE,
)

# Stricter basename variant that additionally captures the rawspec product
# number and requires a ".rawspec.NNNN.h5|fil" suffix (reference:
# src/gbtworkerfunctions.jl:49-61; defined there but never called — kept
# public here for user code, as in the reference).
RAWSPEC_BASE_RE = re.compile(
    GUPPI_BASE_RE.pattern
    + r"""
    \.rawspec\.
    (?P<product>\d{4})
    \.(?:h5|fil)$
    """,
    re.VERBOSE,
)


@dataclass(frozen=True)
class GuppiName:
    """Parsed components of a GUPPI-convention file path.

    ``band``/``bank``/``host`` are None when the path lacks the optional
    ``/BLP<band><bank>/`` component or ``blc??_`` host prefix.  ``product`` is
    only set when parsed by :func:`parse_rawspec_name`.
    """

    imjd: int
    smjd: int
    src: str
    scan: str
    band: Optional[int] = None
    bank: Optional[int] = None
    host: Optional[str] = None
    product: Optional[str] = None


def _parse(name: str, base_re: re.Pattern, require_player: bool) -> Optional[GuppiName]:
    base = name.rsplit("/", 1)[-1]
    m = base_re.search(base)
    if m is None:
        return None
    # Rightmost /BLPbb/ component: the player dir sits closest to the file,
    # so a BLP-like component higher up (e.g. in the root path) must not
    # shadow it.
    pm = None
    for pm in PLAYER_COMPONENT_RE.finditer(name):
        pass
    if require_player and pm is None:
        return None
    g = m.groupdict()
    return GuppiName(
        imjd=int(g["imjd"]),
        smjd=int(g["smjd"]),
        src=g["src"],
        scan=g["scan"],
        band=int(pm.group("band")) if pm else None,
        bank=int(pm.group("bank")) if pm else None,
        host=g.get("host"),
        product=g.get("product"),
    )


def parse_guppi_name(name: str) -> Optional[GuppiName]:
    """Parse a GUPPI-convention path; None if it doesn't match.

    Handles both raw voltage files (``*.NNNN.raw``) and rawspec products
    (``*.rawspec.NNNN.{h5,fil}``), matching the reference ``parseguppiname``
    (src/gbtworkerfunctions.jl:35-47).  ``band``/``bank`` come from the
    ``/BLP<band><bank>/`` path component when present, at any depth.
    """
    return _parse(name, GUPPI_BASE_RE, require_player=False)


def parse_rawspec_name(name: str) -> Optional[GuppiName]:
    """Parse a rawspec product path, requiring the ``/BLPbb/`` path component
    and ``.rawspec.NNNN.{h5,fil}`` suffix (src/gbtworkerfunctions.jl:49-61)."""
    return _parse(name, RAWSPEC_BASE_RE, require_player=True)


def player_name(band: int, bank: int) -> str:
    """The logical recording-node name ``BLP<band><bank>``
    (reference: README.md:21-23)."""
    if not (0 <= band <= 7 and 0 <= bank <= 7):
        raise ValueError(f"band and bank must be in 0..7, got {band},{bank}")
    return f"BLP{band}{bank}"
