"""blit.io — host-side file-format codecs.

Replaces the reference's dependency layer (SURVEY.md §2.2): Blio.jl (SIGPROC
filterbank + GUPPI RAW), HDF5.jl + H5Zbitshuffle.jl (FBH5).  Pure-Python/NumPy
with optional C++ acceleration from ``blit/native``.
"""

from blit.io.sigproc import read_fil_header, read_fil_data, write_fil
from blit.io.fbh5 import is_hdf5, read_fbh5_header, read_fbh5_data, write_fbh5
from blit.io.hits import read_hits, write_hits
from blit.io.guppi import (
    GuppiRaw,
    GuppiScan,
    open_raw,
    read_raw_header,
    scan_files,
    write_raw,
)

__all__ = [
    "read_fil_header",
    "read_fil_data",
    "write_fil",
    "is_hdf5",
    "read_fbh5_header",
    "read_fbh5_data",
    "write_fbh5",
    "read_hits",
    "write_hits",
    "GuppiRaw",
    "GuppiScan",
    "open_raw",
    "scan_files",
    "read_raw_header",
    "write_raw",
]
