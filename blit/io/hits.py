"""``.hits`` product codec: atomic-publish, resumable hit-list writers.

The search plane's product is RAGGED — a variable number of hit records
per time window — so it gets its own line-oriented format instead of a
fixed-shape slab: JSON lines, first line a header record (``kind``,
format version, the full search/filterbank header), then one line per
hit in stream order.  JSON-lines because hit lists are small (the whole
point of on-device search is that only hits cross the wire), humans
triage them directly (docs/WORKFLOWS.md), and byte-determinism is easy
to pin: ``sort_keys=True`` everywhere, floats via the default repr.

Writer contracts mirror the filterbank writers (blit/io/sigproc.py,
blit/io/fbh5.py) so the async output plane drives them unchanged:

- :class:`HitsWriter` streams into a ``.partial`` sibling renamed on
  success — a crash never leaves a complete-looking truncated product.
- :class:`ResumableHitsWriter` appends directly, with a cursor sidecar
  (:class:`blit.search.dedoppler.SearchCursor`) claiming windows only
  AFTER their lines are fsync'd — the ResumableFilWriter durability
  ordering.  ``abort()`` keeps file + cursor as the resume point.
- Both expose ``append(WindowHits)`` / ``flush`` / ``close`` /
  ``abort`` / ``nsamps``, and :class:`WindowHits` carries ``nbytes``,
  so :class:`blit.outplane.AsyncSink` write-behind (bounded queue,
  flush barriers, ``sink.write`` fault injection) works on hit lists
  exactly as on spectra slabs — the ragged sink path of ISSUE 6.
"""

from __future__ import annotations

import json
import os
from typing import Dict, List, Optional, Tuple

HITS_KIND = "blit.hits"
HITS_VERSION = 1

# Bound on a resumable writer's per-window claim ledger
# (``cursor.window_claims`` — ``[window, byte_offset, hits]`` triples):
# every append re-serializes + fsyncs the whole cursor, so the ledger
# must not grow with session length.  A restart further back than the
# trimmed tail is UNRESOLVABLE and refused loudly (never silently
# mis-resumed) — in practice unreachable: the sharded loop keeps pod
# claims within the sink depth of each other, orders of magnitude
# under this bound.
CLAIM_LEDGER_MAX = 4096


def ledger_claim_at(windows: int, windows_done: int, byte_offset: int,
                    hits_done: int, claims) -> Optional[Tuple[int, int]]:
    """The ONE ledger-resolution rule both cursor kinds share
    (SearchCursor / StreamCursor): the head claim resolves directly;
    earlier windows resolve through a ``[window, byte_offset, hits]``
    ledger entry; anything else — absent ledger, trimmed-away window —
    is None (the caller refuses, it never guesses)."""
    if windows == windows_done:
        return byte_offset, hits_done
    if claims is None or windows <= 0:
        return None
    for w, off, hits in reversed(claims):
        if w == windows:
            return int(off), int(hits)
    return None


def _jsonable(header: Dict) -> Dict:
    import numpy as np

    out = {}
    for k, v in header.items():
        if isinstance(v, np.generic):
            v = v.item()
        out[k] = v
    return out


def header_line(header: Dict) -> str:
    """The deterministic first line of a ``.hits`` file."""
    return json.dumps(
        {"kind": HITS_KIND, "version": HITS_VERSION,
         "header": _jsonable(header)},
        sort_keys=True, default=str,
    ) + "\n"


class WindowHits:
    """One window's hit list, pre-serialized — the ragged slab the
    async sink queues (its ``nbytes`` is what the ``write`` stage
    accounts)."""

    __slots__ = ("window", "hits", "lines")

    def __init__(self, window: int, hits: List) -> None:
        self.window = window
        self.hits = hits
        self.lines = "".join(
            json.dumps(h.record(), sort_keys=True) + "\n" for h in hits
        )

    @property
    def nbytes(self) -> int:
        return len(self.lines)


class HitsWriter:
    """Streaming ``.hits`` writer with the ``.partial``-rename publish
    rule (module docstring).  ``nsamps`` counts hits written — the
    writer-contract name every sink already speaks."""

    def __init__(self, path: str, header: Dict) -> None:
        from blit import integrity

        self.path = path
        self._tmp = path + ".partial"
        self._f = open(self._tmp, "w")
        hl = header_line(header)
        self._f.write(hl)
        # Product manifest (ISSUE 13): the running CRC folds every byte
        # in write order, so the completed running CRC IS the whole-file
        # digest; published as <product>.manifest.json at close.
        self._mf = integrity.ManifestWriter(
            path, "hits", writer=type(self).__name__)
        self._mf.fold(hl.encode())
        self.nsamps = 0
        self.nwindows = 0

    def append(self, wh: WindowHits) -> None:
        self._f.write(wh.lines)
        self.nsamps += len(wh.hits)
        self.nwindows += 1
        self._mf.fold(wh.lines.encode())
        self._mf.claim(self.nwindows)

    def flush(self) -> None:
        self._f.flush()
        os.fsync(self._f.fileno())

    def close(self) -> None:
        self.flush()
        self._f.close()
        os.replace(self._tmp, self.path)
        self._mf.publish()

    def abort(self) -> None:
        """Error-path teardown: drop the ``.partial`` (never leave a
        complete-looking product)."""
        try:
            self._f.close()
        finally:
            try:
                os.unlink(self._tmp)
            except OSError:
                pass


class ResumableHitsWriter:
    """Append-directly ``.hits`` writer whose incompleteness marker is a
    cursor sidecar: window lines are fsync'd BEFORE the cursor claims
    them, so a crash leaves a resumable prefix, never a cursor ahead of
    the bytes.  ``start_windows`` > 0 resumes — the file is truncated to
    the cursor's claimed byte offset (dropping any un-checkpointed
    tail); 0 or a missing file starts fresh."""

    def __init__(self, path: str, header: Dict, start_windows: int,
                 cursor) -> None:
        from blit import integrity

        self.path = path
        self.cursor = cursor
        self._mf = integrity.ManifestWriter(
            path, "hits", writer=type(self).__name__)
        if start_windows > 0 and os.path.exists(path):
            # The restart may sit EARLIER than this cursor's own claim
            # (the sharded plane restarts at the pod-wide-agreed minimum,
            # ISSUE 12): resolve the byte/hit claim at start_windows from
            # the cursor's per-window ledger and clamp DOWN — truncating
            # at the cursor's own head claim but calling it start_windows
            # would splice later windows mid-product.
            if hasattr(cursor, "claim_at"):
                claim = cursor.claim_at(start_windows)
            else:  # ledger-less duck-typed cursor: head claim only
                claim = ((cursor.byte_offset, cursor.hits_done)
                         if start_windows == cursor.windows_done
                         else None)
            if claim is None:
                # Refuse LOUDLY: pretending to resume at start_windows
                # while truncating somewhere else would duplicate (or
                # drop) windows mid-product — the caller must restart
                # the player fresh instead.
                raise ValueError(
                    f"{path}: cursor cannot resolve a truncation point "
                    f"for window {start_windows} (claimed "
                    f"{cursor.windows_done}; claim ledger absent or "
                    f"trimmed) — delete the sidecar to restart fresh")
            off, hits = claim
            with open(path, "r+b") as f:
                f.truncate(off)
            cursor.windows_done = start_windows
            cursor.hits_done = hits
            cursor.byte_offset = off
            if getattr(cursor, "window_claims", None) is not None:
                cursor.window_claims = [
                    e for e in cursor.window_claims
                    if e[0] <= start_windows
                ]
            cursor.save(path)
            # Rebuild the running digest over the truncated claim
            # (callers content-verified it via verify_hits_claim) and
            # checkpoint the manifest ledger at the restart point.
            self._mf.fold_path(path)
            self._mf.claim(start_windows)
            self._mf.save()
            self._f = open(path, "a")
        else:
            self._f = open(path, "w")
            self._f.write(header_line(header))
            self._f.flush()
            os.fsync(self._f.fileno())
            cursor.windows_done = 0
            cursor.hits_done = 0
            cursor.byte_offset = self._f.tell()
            if hasattr(cursor, "window_claims"):
                cursor.window_claims = []
            cursor.save(path)
            self._mf.fold_path(path)
            self._mf.save()
        # Cumulative across the whole product, resumed windows included
        # (the ResumableFilWriter nsamps = start_rows convention) — the
        # finished header's search_nhits must count every hit line in
        # the file, not just this run's.
        self.nsamps = cursor.hits_done
        self.nwindows = cursor.windows_done

    def append(self, wh: WindowHits) -> None:
        self._f.write(wh.lines)
        # Durable lines BEFORE the cursor claims them (power-loss
        # ordering, the ResumableFilWriter rule).
        self._f.flush()
        os.fsync(self._f.fileno())
        self.nsamps += len(wh.hits)
        self.nwindows += 1
        # Manifest BETWEEN the fsync and the cursor claim (ISSUE 13,
        # the ResumableFilWriter ordering): the ledger then always
        # holds an entry for every window count a cursor can claim —
        # a crash leaves the manifest AHEAD (harmless), never behind.
        self._mf.fold(wh.lines.encode())
        self._mf.claim(self.nwindows)
        self._mf.save()
        self.cursor.windows_done = self.nwindows
        self.cursor.hits_done = self.nsamps
        self.cursor.byte_offset = self._f.tell()
        claims = getattr(self.cursor, "window_claims", None)
        if claims is not None:
            claims.append([self.nwindows, self.cursor.byte_offset,
                           self.nsamps])
            del claims[:-CLAIM_LEDGER_MAX]  # bounded per-append I/O
        self.cursor.save(self.path)

    def flush(self) -> None:
        self._f.flush()
        os.fsync(self._f.fileno())

    def close(self) -> None:
        """Finish: the sidecar's absence is the completeness marker; the
        manifest flips to complete and stays (the fsck surface)."""
        self._f.close()
        self._mf.publish()
        sidecar = self.cursor.path_for(self.path)
        if os.path.exists(sidecar):
            os.unlink(sidecar)

    def abort(self) -> None:
        # The file + cursor ARE the resume point: keep both.
        self._f.close()


def write_hits(path: str, header: Dict, hits: List) -> None:
    """One-shot atomic ``.hits`` publish (in-memory hit list)."""
    w = HitsWriter(path, header)
    try:
        w.append(WindowHits(-1, hits))
    except BaseException:
        w.abort()
        raise
    w.close()


def read_hits(path: str) -> Tuple[Dict, List]:
    """Read a ``.hits`` product → ``(header, hits)`` with hits as
    :class:`blit.search.hits.Hit` objects (lazy import — blit.io stays
    light)."""
    from blit.search.hits import hit_from_record

    header: Optional[Dict] = None
    hits = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            doc = json.loads(line)
            if header is None:
                if doc.get("kind") != HITS_KIND:
                    raise ValueError(
                        f"{path}: not a {HITS_KIND} file "
                        f"(kind={doc.get('kind')!r})"
                    )
                header = doc["header"]
                continue
            hits.append(hit_from_record(doc))
    if header is None:
        raise ValueError(f"{path}: empty .hits file")
    return header, hits
