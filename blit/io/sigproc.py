"""SIGPROC filterbank (.fil) codec.

Replaces Blio.jl's ``Filterbank.Header`` / ``Filterbank.mmap``
(reference usage: src/gbtworkerfunctions.jl:131-139, 171-177).

Format: a binary header of length-prefixed keyword items bracketed by
``HEADER_START``/``HEADER_END``, followed by raw samples.  Sample layout is
time-major — for each time sample, ``nifs`` spectra of ``nchans`` values —
i.e. C-order ``(nsamps, nifs, nchans)``, memory-identical to the reference's
column-major ``(nchans, nifs, nsamps)`` (see blit/ops/fqav.py layout note).
"""

from __future__ import annotations

import os
import struct
from typing import BinaryIO, Dict, Optional, Tuple

import numpy as np

# Keyword -> value type.  The SIGPROC header is self-describing only in
# keyword names, so the codec needs this table (same set Blio.jl understands).
_STRING_KEYS = {"source_name", "rawdatafile"}
_INT_KEYS = {
    "telescope_id",
    "machine_id",
    "data_type",
    "barycentric",
    "pulsarcentric",
    "nbits",
    "nsamples",
    "nchans",
    "nifs",
    "nbeams",
    "ibeam",
    "nbins",
}
_DOUBLE_KEYS = {
    "az_start",
    "za_start",
    "src_raj",
    "src_dej",
    "tstart",
    "tsamp",
    "fch1",
    "foff",
    "refdm",
    "period",
}

_DTYPES = {8: np.uint8, 16: np.uint16, 32: np.float32}


def _read_string(f: BinaryIO) -> str:
    (n,) = struct.unpack("<i", f.read(4))
    if not 0 < n < 256:
        raise ValueError(f"sigproc: implausible header string length {n}")
    return f.read(n).decode("ascii")


def _write_string(f: BinaryIO, s: str) -> None:
    b = s.encode("ascii")
    f.write(struct.pack("<i", len(b)))
    f.write(b)


def read_fil_header(path: str) -> Tuple[Dict, int]:
    """Read a SIGPROC header.  Returns ``(header_dict, data_offset_bytes)``.

    The dict holds the raw on-disk keywords plus computed ``nsamps`` (from
    file size when ``nsamples`` is absent/zero, as Blio does).
    """
    hdr: Dict = {}
    with open(path, "rb") as f:
        magic = _read_string(f)
        if magic != "HEADER_START":
            raise ValueError(f"{path}: not a SIGPROC filterbank file")
        while True:
            key = _read_string(f)
            if key == "HEADER_END":
                break
            if key in _STRING_KEYS:
                hdr[key] = _read_string(f)
            elif key in _INT_KEYS:
                (hdr[key],) = struct.unpack("<i", f.read(4))
            elif key in _DOUBLE_KEYS:
                (hdr[key],) = struct.unpack("<d", f.read(8))
            else:
                raise ValueError(f"{path}: unknown sigproc header keyword {key!r}")
        offset = f.tell()
    nbits = hdr.get("nbits", 32)
    nchans = hdr.get("nchans", 1)
    nifs = hdr.get("nifs", 1)
    sample_bytes = nchans * nifs * nbits // 8
    data_bytes = os.path.getsize(path) - offset
    hdr["nsamps"] = data_bytes // sample_bytes if sample_bytes else 0
    return hdr, offset


def read_fil_data(
    path: str, header: Optional[Dict] = None, mmap: bool = True
) -> Tuple[Dict, np.ndarray]:
    """Return ``(header, data)`` with data shaped ``(nsamps, nifs, nchans)``.

    ``mmap=True`` returns a read-only memmap (the analog of
    ``Filterbank.mmap``, src/gbtworkerfunctions.jl:173); callers slice it and
    the memmap is unmapped when garbage-collected.
    """
    if header is None:
        header, offset = read_fil_header(path)
    else:
        _, offset = read_fil_header(path)
    nbits = header.get("nbits", 32)
    if nbits not in _DTYPES:
        raise ValueError(f"{path}: unsupported nbits={nbits}")
    # Header-vs-payload cross-check (ISSUE 13 satellite, closing the
    # gap the validate_slab docstring documents): SIGPROC derives nsamps
    # from file size, so a payload that is not a whole number of
    # (nifs, nchans) spectra means the header lies about the layout
    # (torn write, wrong nchans/nbits, foreign bytes) — REFUSE with a
    # clear error instead of returning a silently mis-shaped array.
    nifs = header.get("nifs", 1)
    nchans = header["nchans"]
    sample_bytes = nchans * nifs * nbits // 8
    payload = os.path.getsize(path) - offset
    if sample_bytes <= 0 or payload % sample_bytes:
        raise ValueError(
            f"{path}: payload of {payload} bytes is not a whole number "
            f"of (nifs={nifs}, nchans={nchans}, nbits={nbits}) spectra "
            f"of {sample_bytes} bytes — truncated or corrupt product "
            "(header disagrees with the bytes on disk)"
        )
    shape = (header["nsamps"], header.get("nifs", 1), header["nchans"])
    if mmap:
        data = np.memmap(path, dtype=_DTYPES[nbits], mode="r", offset=offset, shape=shape)
    else:
        with open(path, "rb") as f:
            f.seek(offset)
            data = np.fromfile(f, dtype=_DTYPES[nbits]).reshape(shape)
    return header, data



def validate_slab(slab: np.ndarray, nifs: int, nchans: int,
                  dtype: np.dtype) -> np.ndarray:
    """The SIGPROC slab guard, shared by every ``.fil`` append path
    (FilWriter here and blit.pipeline.ResumableFilWriter): SIGPROC derives
    nsamps from file size, so a mis-shaped or mis-typed slab would write a
    valid-looking corrupt product nothing downstream can detect.  Shape
    must match exactly; dtype is coerced only within the same kind
    (float64→float32 fine; float→uint8 would silently wrap sample values
    and is refused)."""
    if slab.ndim != 3 or slab.shape[1:] != (nifs, nchans):
        raise ValueError(
            f"append: slab shape {slab.shape} does not extend "
            f"(*, {nifs}, {nchans})"
        )
    if slab.dtype != dtype:
        slab = slab.astype(dtype, casting="same_kind")
    return np.ascontiguousarray(slab)


class FilWriter:
    """Streaming ``.fil`` slab writer with ``.partial`` atomicity — the
    SIGPROC twin of :class:`blit.io.fbh5.FBH5Writer`'s append interface.
    SIGPROC derives nsamps from file size, so append-only streaming is
    exact; bytes land in a ``.partial`` sibling renamed on :meth:`close`
    (a crash mid-stream must not leave a valid-looking truncated product).
    Backs both ``RawReducer.reduce_to_file`` and the mesh scan writer
    (blit/parallel/scan.py) so the atomicity protocol lives in one place.
    """

    def __init__(self, path: str, header: Dict, nifs: int, nchans: int,
                 dtype=np.float32):
        import os as _os

        from blit import integrity

        self.final_path = path
        self.path = path + ".partial"
        self._os = _os
        self.nifs = nifs
        self.nchans = nchans
        self.dtype = np.dtype(dtype)
        write_fil(self.path, header, np.zeros((0, nifs, nchans), dtype))
        # Product manifest (ISSUE 13): per-window digests + whole-file
        # CRC, folded as slabs append (this runs on the write-behind
        # sink thread under the async plane — digesting rides the
        # thread that already owns the bytes) and published as a
        # <product>.manifest.json sidecar at close.
        self._mf = integrity.ManifestWriter(
            self.final_path, "fil",
            row_bytes=nifs * nchans * self.dtype.itemsize,
            writer=type(self).__name__)
        self._mf.data_offset = _os.path.getsize(self.path)
        self._mf.fold_path(self.path)
        self._f = open(self.path, "ab")
        self.nsamps = 0

    def append(self, slab: np.ndarray) -> None:
        """Append ``(k, nifs, nchans)`` spectra (validated + same-kind
        dtype-coerced by :func:`validate_slab`)."""
        slab = validate_slab(slab, self.nifs, self.nchans, self.dtype)
        slab.tofile(self._f)
        self.nsamps += slab.shape[0]
        self._mf.fold(slab)
        self._mf.claim(self.nsamps)

    def flush(self) -> None:
        """Push appended bytes to the OS — the write-behind sink's flush
        barrier hook (:meth:`blit.outplane.AsyncSink.flush`).  Durability
        (fsync) stays the resumable writers' job; the atomic-publish
        rename on :meth:`close` is this writer's completion marker."""
        if self._f is not None:
            self._f.flush()

    def close(self) -> None:
        if self._f is None:
            return
        try:
            self._f.close()
            self._f = None
            self._os.replace(self.path, self.final_path)
        except BaseException:
            self.abort()
            raise
        # After the atomic publish: the manifest sidecar (best-effort —
        # a manifest-write failure must never un-publish the product).
        self._mf.publish()

    def abort(self) -> None:
        """Drop the partial product (crash/exception path)."""
        if self._f is not None:
            self._f.close()
            self._f = None
        if self._os.path.exists(self.path):
            self._os.unlink(self.path)

    def __enter__(self):
        return self

    def __exit__(self, etype, _e, _tb):
        if etype is None:
            self.close()
        else:
            self.abort()


def write_fil(path: str, header: Dict, data: np.ndarray) -> None:
    """Write a SIGPROC filterbank file.

    ``data`` must be shaped ``(nsamps, nifs, nchans)``; dtype determines
    ``nbits``.  Header keywords not in the SIGPROC keyword tables are ignored
    (so normalized headers round-trip).
    """
    if data.ndim != 3:
        raise ValueError("write_fil: data must be (nsamps, nifs, nchans)")
    nbits = {np.uint8: 8, np.uint16: 16, np.float32: 32}[data.dtype.type]
    hdr = dict(header)
    hdr["nbits"] = nbits
    hdr["nchans"] = data.shape[2]
    hdr["nifs"] = data.shape[1]
    with open(path, "wb") as f:
        _write_string(f, "HEADER_START")
        for key, val in hdr.items():
            if key in _STRING_KEYS:
                _write_string(f, key)
                _write_string(f, str(val))
            elif key in _INT_KEYS:
                _write_string(f, key)
                f.write(struct.pack("<i", int(val)))
            elif key in _DOUBLE_KEYS:
                _write_string(f, key)
                f.write(struct.pack("<d", float(val)))
            # silently skip computed/unknown keys (nsamps, nfpc, data_size, ...)
        _write_string(f, "HEADER_END")
        np.ascontiguousarray(data).tofile(f)
