"""GUPPI RAW voltage-file codec.

Replaces Blio.jl's GUPPI RAW support (SURVEY.md §2.2).  A RAW file is a
sequence of blocks, each a FITS-like header (80-byte ``KEY = value`` cards,
terminated by ``END``) followed by ``BLOCSIZE`` bytes of 8-bit complex
voltages laid out channel-major:

    [OBSNCHAN coarse channels][ntime samples][npol pols][2 int8 (re, im)]

with ``ntime = BLOCSIZE / (OBSNCHAN * npol * 2)``.  ``NPOL=4`` in headers
means 2 polarizations of complex data (the GUPPI convention).  When
``DIRECTIO=1`` the header is padded to a 512-byte boundary.  ``OVERLAP`` time
samples at the end of each block repeat at the start of the next — the PFB
state-carry the reference never handled (its RAW path stops at inventory;
SURVEY.md §7 "hard parts").
"""

from __future__ import annotations

import os
from typing import Dict, Iterator, List, Optional, Tuple

import numpy as np

CARD_LEN = 80
DIRECTIO_ALIGN = 512


def _parse_card_value(raw: str):
    s = raw.strip()
    if s.startswith("'"):
        return s.strip("'").rstrip()
    try:
        return int(s)
    except ValueError:
        pass
    try:
        return float(s)
    except ValueError:
        pass
    return s


def _format_card(key: str, value) -> bytes:
    if isinstance(value, str):
        vs = f"'{value:<8s}'"
    elif isinstance(value, bool):
        vs = "T" if value else "F"
    elif isinstance(value, float):
        vs = f"{value:.12G}"
    else:
        vs = str(value)
    card = f"{key:<8s}= {vs}"
    if len(card) > CARD_LEN:
        raise ValueError(f"guppi card too long: {card!r}")
    return card.ljust(CARD_LEN).encode("ascii")


def read_raw_header(f) -> Tuple[Dict, int]:
    """Read one block header from the current file position.

    Returns ``(header, data_offset)`` where ``data_offset`` accounts for
    DIRECTIO padding.  Raises ``EOFError`` at end of file.
    """
    hdr: Dict = {}
    start = f.tell()
    while True:
        card = f.read(CARD_LEN)
        if len(card) < CARD_LEN:
            if not hdr and len(card) == 0:
                raise EOFError
            raise ValueError("guppi: truncated header card")
        text = card.decode("ascii", errors="replace")
        key = text[:8].strip()
        if key == "END":
            break
        if "=" not in text:
            raise ValueError(f"guppi: malformed card {text!r}")
        hdr[key] = _parse_card_value(text.split("=", 1)[1])
    end = f.tell()
    if hdr.get("DIRECTIO", 0):
        pad = (-(end - start)) % DIRECTIO_ALIGN
        f.seek(pad, os.SEEK_CUR)
    return hdr, f.tell()


def block_ntime(hdr: Dict) -> int:
    """Time samples per block implied by the header."""
    npol = 2 if hdr["NPOL"] > 2 else hdr["NPOL"]
    nbits = hdr.get("NBITS", 8)
    bytes_per_samp = hdr["OBSNCHAN"] * npol * 2 * nbits // 8
    return hdr["BLOCSIZE"] // bytes_per_samp


class GuppiRaw:
    """One GUPPI RAW file: indexed access to (header, voltage-block) pairs.

    Scans block boundaries once at construction (headers only — cheap), then
    reads blocks on demand.  When the native threaded reader is built
    (``make -C blit/native``) block reads fan ``pread`` across threads at
    storage/pagecache bandwidth — the GB/s host-side feed SURVEY.md §2.3
    prescribes; otherwise single-threaded memmap slices serve (large files
    still never fully load).

    ``native``: ``None`` auto-detects the built library; ``True`` requires
    it; ``False`` forces the memmap path.
    """

    def __init__(self, path: str, native: Optional[bool] = None):
        self.path = path
        self.headers: List[Dict] = []
        self._data_offsets: List[int] = []
        if native is None or native:
            from blit.io.native import guppi_lib

            have = guppi_lib() is not None
            if native and not have:
                raise RuntimeError(
                    "native GUPPI reader unbuilt: make -C blit/native"
                )
            self.native = have
        else:
            self.native = False
        with open(path, "rb") as f:
            size = os.path.getsize(path)
            while True:
                try:
                    hdr, off = read_raw_header(f)
                except EOFError:
                    break
                if off + hdr["BLOCSIZE"] > size:
                    break  # truncated trailing block
                self.headers.append(hdr)
                self._data_offsets.append(off)
                f.seek(hdr["BLOCSIZE"], os.SEEK_CUR)

    @property
    def nblocks(self) -> int:
        return len(self.headers)

    def header(self, i: int = 0) -> Dict:
        return self.headers[i]

    def _block_geometry(self, i: int) -> Tuple[int, int, int]:
        """(nchan, ntime, npol) of block ``i`` after NBITS validation."""
        hdr = self.headers[i]
        nbits = hdr.get("NBITS", 8)
        if nbits != 8:
            raise NotImplementedError(f"NBITS={nbits} not supported (GBT uses 8)")
        npol = 2 if hdr["NPOL"] > 2 else hdr["NPOL"]
        return hdr["OBSNCHAN"], block_ntime(hdr), npol

    def read_block(self, i: int) -> np.ndarray:
        """Raw int8 voltages of block ``i``, shaped
        ``(obsnchan, ntime, npol, 2)`` (last axis = re, im).

        Native path: one threaded read into a fresh buffer.  Fallback: a lazy
        memmap view (pages in on consumption, single-threaded)."""
        nchan, ntime, npol = self._block_geometry(i)
        shape = (nchan, ntime, npol, 2)
        if self.native:
            from blit.io.native import guppi_pread

            nbytes = nchan * ntime * npol * 2
            buf = guppi_pread(self.path, self._data_offsets[i], nbytes)
            return buf.view(np.int8).reshape(shape)
        return np.memmap(
            self.path,
            dtype=np.int8,
            mode="r",
            offset=self._data_offsets[i],
            shape=shape,
        )

    def read_block_into(
        self, i: int, dst: np.ndarray, t0: int = 0, ntime_keep: int = -1
    ) -> int:
        """Read samples ``[t0, t0+ntime_keep)`` of every channel of block
        ``i`` directly into ``dst[:, :ntime_keep]`` — the zero-intermediate-
        copy feed for the streaming ring buffer (blit/pipeline.py).

        ``dst``: int8 ``(nchan, >=ntime_keep, npol, 2)`` with C-contiguous
        rows (a time-slice view of a C-contiguous ring buffer qualifies).
        ``ntime_keep=-1`` means through the end of the block.  Returns the
        samples written.  Uses the native strided pread when built, else a
        memmap copy.
        """
        nchan, ntime, npol = self._block_geometry(i)
        if ntime_keep < 0:
            ntime_keep = ntime - t0
        if t0 < 0 or t0 + ntime_keep > ntime:
            raise ValueError(
                f"read_block_into: [{t0}, {t0 + ntime_keep}) outside block "
                f"of {ntime} samples"
            )
        if dst.dtype != np.int8 or dst.shape[0] != nchan or dst.shape[2:] != (npol, 2):
            raise ValueError("read_block_into: dst shape/dtype mismatch")
        if ntime_keep == 0:
            return 0
        samp_bytes = npol * 2
        if self.native and dst[0].flags.c_contiguous:
            from blit.io.native import guppi_pread_strided

            guppi_pread_strided(
                self.path,
                self._data_offsets[i] + t0 * samp_bytes,
                nchan,
                ntime_keep * samp_bytes,
                ntime * samp_bytes,
                dst,
                dst.strides[0],
            )
            return ntime_keep
        mm = np.memmap(
            self.path,
            dtype=np.int8,
            mode="r",
            offset=self._data_offsets[i],
            shape=(nchan, ntime, npol, 2),
        )
        dst[:, :ntime_keep] = mm[:, t0 : t0 + ntime_keep]
        return ntime_keep

    def block_ntime_kept(self, i: int) -> int:
        """Time samples block ``i`` contributes to the gap-free stream: its
        trailing ``OVERLAP`` samples repeat at the start of the next block,
        so every block but the last drops them."""
        hdr = self.headers[i]
        nt = block_ntime(hdr)
        if i < self.nblocks - 1:
            nt -= hdr.get("OVERLAP", 0)
        return nt

    def read_block_complex(self, i: int) -> np.ndarray:
        """Block ``i`` as complex64, shaped ``(obsnchan, ntime, npol)``."""
        b = self.read_block(i).astype(np.float32)
        return (b[..., 0] + 1j * b[..., 1]).astype(np.complex64)

    def iter_blocks(
        self, drop_overlap: bool = False
    ) -> Iterator[Tuple[Dict, np.ndarray]]:
        """Yield ``(header, block)`` pairs; ``drop_overlap=True`` trims the
        trailing ``OVERLAP`` samples of every block except the last, giving a
        gap-free concatenation along time."""
        for i in range(self.nblocks):
            hdr = self.headers[i]
            block = self.read_block(i)
            if drop_overlap and i < self.nblocks - 1:
                ov = hdr.get("OVERLAP", 0)
                if ov:
                    block = block[:, :-ov]
            yield hdr, block

    def time_span_s(self) -> float:
        """Total (overlap-corrected) duration covered by the file."""
        if not self.headers:
            return 0.0
        tbin = self.headers[0].get("TBIN", 0.0)
        total = 0
        for i, hdr in enumerate(self.headers):
            nt = block_ntime(hdr)
            if i < self.nblocks - 1:
                nt -= hdr.get("OVERLAP", 0)
            total += nt
        return total * tbin


def write_raw(
    path: str,
    header: Dict,
    blocks: List[np.ndarray],
    directio: bool = False,
) -> None:
    """Write a GUPPI RAW file (fixture generator and pipeline output).

    ``blocks``: int8 arrays shaped ``(obsnchan, ntime, npol, 2)``.  Per-block
    headers are derived from ``header`` with ``BLOCSIZE``/``PKTIDX`` updated.
    """
    hdr = dict(header)
    hdr["DIRECTIO"] = 1 if directio else 0
    pktidx = int(hdr.get("PKTIDX", 0))
    with open(path, "wb") as f:
        for blk in blocks:
            if blk.dtype != np.int8 or blk.ndim != 4 or blk.shape[3] != 2:
                raise ValueError("write_raw: blocks must be int8 (nchan, ntime, npol, 2)")
            nchan, ntime, npol, _ = blk.shape
            hdr["OBSNCHAN"] = nchan
            hdr["NPOL"] = 4 if npol == 2 else npol
            hdr["NBITS"] = 8
            hdr["BLOCSIZE"] = blk.nbytes
            hdr["PKTIDX"] = pktidx
            pktidx += ntime - int(hdr.get("OVERLAP", 0))
            cards = b"".join(_format_card(k, v) for k, v in hdr.items())
            cards += "END".ljust(CARD_LEN).encode("ascii")
            f.write(cards)
            if directio:
                f.write(b"\x00" * ((-len(cards)) % DIRECTIO_ALIGN))
            f.write(np.ascontiguousarray(blk).tobytes())
