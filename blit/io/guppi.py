"""GUPPI RAW voltage-file codec.

Replaces Blio.jl's GUPPI RAW support (SURVEY.md §2.2).  A RAW file is a
sequence of blocks, each a FITS-like header (80-byte ``KEY = value`` cards,
terminated by ``END``) followed by ``BLOCSIZE`` bytes of 8-bit complex
voltages laid out channel-major:

    [OBSNCHAN coarse channels][ntime samples][npol pols][2 int8 (re, im)]

with ``ntime = BLOCSIZE / (OBSNCHAN * npol * 2)``.  ``NPOL=4`` in headers
means 2 polarizations of complex data (the GUPPI convention).  When
``DIRECTIO=1`` the header is padded to a 512-byte boundary.  ``OVERLAP`` time
samples at the end of each block repeat at the start of the next — the PFB
state-carry the reference never handled (its RAW path stops at inventory;
SURVEY.md §7 "hard parts").
"""

from __future__ import annotations

import glob
import logging
import os
import re
import time
import zlib
from typing import Dict, Iterator, List, Optional, Sequence, Tuple, Union

import numpy as np

from blit import faults

log = logging.getLogger("blit.guppi")

CARD_LEN = 80
DIRECTIO_ALIGN = 512

# A scan is recorded as a *sequence* of files sharing a stem:
#   guppi_<imjd>_<smjd>_[n_]<src>_<scan>.0000.raw, .0001.raw, ...
# — the NNNN in the reference's filename grammar
# (src/gbtworkerfunctions.jl:35-47; README.md:25-27).  The block stream
# continues across file boundaries (same OVERLAP convention), so a whole
# scan must be reduced as one gap-free stream (rawspec parity).
SEQ_RE = re.compile(r"^(?P<stem>.+)\.(?P<seq>\d{4})\.raw$")


def _parse_card_value(raw: str):
    s = raw.strip()
    if s.startswith("'"):
        return s.strip("'").rstrip()
    try:
        return int(s)
    except ValueError:
        pass
    try:
        return float(s)
    except ValueError:
        pass
    return s


def _format_card(key: str, value) -> bytes:
    if isinstance(value, str):
        vs = f"'{value:<8s}'"
    elif isinstance(value, bool):
        vs = "T" if value else "F"
    elif isinstance(value, float):
        vs = f"{value:.12G}"
    else:
        vs = str(value)
    card = f"{key:<8s}= {vs}"
    if len(card) > CARD_LEN:
        raise ValueError(f"guppi card too long: {card!r}")
    return card.ljust(CARD_LEN).encode("ascii")


def read_raw_header(f) -> Tuple[Dict, int]:
    """Read one block header from the current file position.

    Returns ``(header, data_offset)`` where ``data_offset`` accounts for
    DIRECTIO padding.  Raises ``EOFError`` at end of file.
    """
    hdr: Dict = {}
    start = f.tell()
    while True:
        card = f.read(CARD_LEN)
        if len(card) < CARD_LEN:
            if not hdr and len(card) == 0:
                raise EOFError
            raise ValueError("guppi: truncated header card")
        text = card.decode("ascii", errors="replace")
        key = text[:8].strip()
        if key == "END":
            break
        if "=" not in text:
            raise ValueError(f"guppi: malformed card {text!r}")
        hdr[key] = _parse_card_value(text.split("=", 1)[1])
    end = f.tell()
    if hdr.get("DIRECTIO", 0):
        pad = (-(end - start)) % DIRECTIO_ALIGN
        f.seek(pad, os.SEEK_CUR)
    return hdr, f.tell()


def block_ntime(hdr: Dict) -> int:
    """Time samples per block implied by the header."""
    npol = 2 if hdr["NPOL"] > 2 else hdr["NPOL"]
    nbits = hdr.get("NBITS", 8)
    bytes_per_samp = hdr["OBSNCHAN"] * npol * 2 * nbits // 8
    return hdr["BLOCSIZE"] // bytes_per_samp


class _BlockStream:
    """Shared gap-free-stream semantics over an indexed block sequence.

    Subclasses provide ``nblocks``, ``header(i)`` and ``read_block(i)``; this
    base owns the one overlap-trim rule (every block but the stream's last
    drops its trailing ``OVERLAP`` samples — they repeat at the start of the
    next block, whether or not a file boundary intervenes).
    """

    def block_ntime_kept(self, i: int) -> int:
        """Time samples block ``i`` contributes to the gap-free stream."""
        hdr = self.header(i)
        nt = block_ntime(hdr)
        if i < self.nblocks - 1:
            nt -= hdr.get("OVERLAP", 0)
        return nt

    def iter_blocks(
        self, drop_overlap: bool = False
    ) -> Iterator[Tuple[Dict, np.ndarray]]:
        """Yield ``(header, block)`` pairs; ``drop_overlap=True`` trims the
        trailing ``OVERLAP`` samples of every block except the last, giving a
        gap-free concatenation along time."""
        for i in range(self.nblocks):
            hdr = self.header(i)
            block = self.read_block(i)
            if drop_overlap and i < self.nblocks - 1:
                ov = hdr.get("OVERLAP", 0)
                if ov:
                    block = block[:, :-ov]
            yield hdr, block

    def time_span_s(self) -> float:
        """Total (overlap-corrected) duration covered by the stream."""
        if not self.nblocks:
            return 0.0
        tbin = self.header(0).get("TBIN", 0.0)
        return sum(self.block_ntime_kept(i) for i in range(self.nblocks)) * tbin


class GuppiRaw(_BlockStream):
    """One GUPPI RAW file: indexed access to (header, voltage-block) pairs.

    Scans block boundaries once at construction (headers only — cheap), then
    reads blocks on demand.  When the native threaded reader is built
    (``make -C blit/native``) block reads fan ``pread`` across threads at
    storage/pagecache bandwidth — the GB/s host-side feed SURVEY.md §2.3
    prescribes; otherwise single-threaded memmap slices serve (large files
    still never fully load).

    ``native``: ``None`` auto-detects the built library; ``True`` requires
    it; ``False`` forces the memmap path.
    """

    def __init__(self, path: str, native: Optional[bool] = None):
        self.path = path
        self.headers: List[Dict] = []
        self._data_offsets: List[int] = []
        self._pread_fd: Optional[int] = None  # lazy readinto descriptor
        if native is None or native:
            from blit.io.native import guppi_lib

            have = guppi_lib() is not None
            if native and not have:
                raise RuntimeError(
                    "native GUPPI reader unbuilt: make -C blit/native"
                )
            self.native = have
        else:
            self.native = False
        def _scan():
            # Retried as a unit: a transient failure mid-scan must not
            # leave a half-indexed file behind (faults.retry_io classifies
            # — FileNotFoundError etc. stay immediate).
            faults.fire("guppi.open", key=path)
            headers, offsets = [], []
            with open(path, "rb") as f:
                size = os.path.getsize(path)
                while True:
                    try:
                        hdr, off = read_raw_header(f)
                    except EOFError:
                        break
                    if off + hdr["BLOCSIZE"] > size:
                        break  # truncated trailing block
                    headers.append(hdr)
                    offsets.append(off)
                    f.seek(hdr["BLOCSIZE"], os.SEEK_CUR)
            return headers, offsets

        self.headers, self._data_offsets = faults.retry_io(
            _scan, describe=f"guppi open {path}"
        )
        # Ingest verification (ISSUE 13): when a per-member digest
        # sidecar exists (<path>.digests.json, blit/integrity.py) every
        # delivered block is verified — the on-disk payload against the
        # sidecar at first touch (bit rot), the delivered frame against
        # the on-disk bytes per delivery (an in-flight flip, the seeded
        # ``corrupt`` fault mode's shape) — and a mismatched block is
        # ZERO-FILLED (the PR 2/7 zero-weight mask discipline applied to
        # blocks: it contributes nothing downstream) instead of
        # propagating garbage.  bad_blocks is the per-reader mask set the
        # reducer surfaces into the product header (_masked_blocks).
        self.bad_blocks: set = set()
        self._block_digests: Optional[List[int]] = None
        self._digest_ok_memo: Dict[int, bool] = {}
        self._integrity_dumped = False
        self._verify_map: Optional[np.ndarray] = None  # lazy flat mmap
        from blit import integrity

        if integrity.ingest_verify_enabled():
            # Raises IntegrityError on a sidecar that exists but does
            # not parse — never reduce against an untrustworthy sidecar.
            self._block_digests = integrity.load_raw_digests(path)

    @property
    def nblocks(self) -> int:
        return len(self.headers)

    def header(self, i: int = 0) -> Dict:
        return self.headers[i]

    def _block_geometry(self, i: int) -> Tuple[int, int, int]:
        """(nchan, ntime, npol) of block ``i`` after NBITS validation."""
        hdr = self.headers[i]
        nbits = hdr.get("NBITS", 8)
        if nbits != 8:
            raise NotImplementedError(f"NBITS={nbits} not supported (GBT uses 8)")
        npol = 2 if hdr["NPOL"] > 2 else hdr["NPOL"]
        return hdr["OBSNCHAN"], block_ntime(hdr), npol

    # -- ingest verification (ISSUE 13) ---------------------------------
    def _mark_bad(self, i: int, why: str) -> None:
        """Record block ``i`` as failed verification: counter + flight
        dump (forced once per reader — the incident trail must exist)
        + the mask set the reducer mirrors into the product header."""
        if i in self.bad_blocks:
            return
        self.bad_blocks.add(i)
        self._digest_ok_memo[i] = False
        faults.incr("integrity.bad_block")
        log.error(
            "%s block %d %s; masking it to zero weight and continuing "
            "degraded", self.path, i, why,
        )
        try:
            from blit.observability import flight_recorder

            rec = flight_recorder()
            rec.event("integrity", "bad_block", path=self.path, block=i,
                      why=why)
            rec.dump(
                f"integrity: {self.path} block {i} {why}; delivered "
                "zero-filled (masked) instead of propagating garbage",
                force=not self._integrity_dumped,
            )
            self._integrity_dumped = True
        except Exception:  # noqa: BLE001 — telemetry must not fail reads
            pass

    def _digest_ok(self, i: int) -> bool:
        """Memoized on-disk check of block ``i``: CRC of the payload
        bytes on disk against the sidecar (bit rot / a flipped byte on
        the archive).  Runs once per block, on the reading thread, from
        pages the read itself just pulled hot."""
        ok = self._digest_ok_memo.get(i)
        if ok is not None:
            return ok
        from blit import integrity

        digests = self._block_digests
        if digests is None or i >= len(digests):
            # Sidecar shorter than the recording (it grew since the
            # digests were taken): the extra blocks are unverifiable,
            # not bad — deliver them unchecked, as without a sidecar.
            self._digest_ok_memo[i] = True
            return True
        t0 = time.perf_counter()
        off = self._data_offsets[i]
        mm = self._vmap()
        crc = zlib.crc32(
            mm[off:off + int(self.headers[i]["BLOCSIZE"])]) & 0xFFFFFFFF
        integrity.observe_verify(time.perf_counter() - t0)
        ok = crc == digests[i]
        if not ok:
            self._mark_bad(i, "failed its on-disk digest "
                               f"({integrity.hex_crc(crc)} != "
                               f"{integrity.hex_crc(digests[i])})")
        self._digest_ok_memo[i] = ok
        return ok

    def _vmap(self) -> np.ndarray:
        """The verification view: ONE flat byte memmap over the whole
        file, built lazily and reused across deliveries (a per-delivery
        mmap would dominate verification cost on small blocks)."""
        if self._verify_map is None:
            self._verify_map = np.memmap(self.path, dtype=np.uint8,
                                         mode="r")
        return self._verify_map

    def _delivery_ok(self, i: int, dst: np.ndarray, t0: int,
                     nt: int) -> bool:
        """Per-delivery check: the DELIVERED region against the same
        region on disk (catches an in-flight flip — the seeded
        ``corrupt`` fault mode — after the disk itself verified).
        memcmp, not a digest: the disk already verified against the
        sidecar, so equality IS correctness here, and a vectorized
        compare costs a fraction of a second CRC pass."""
        nchan, ntime, npol = self._block_geometry(i)
        samp = npol * 2
        row = ntime * samp
        base = self._data_offsets[i] + t0 * samp
        mm = self._vmap()
        t_start = time.perf_counter()
        try:
            for c in range(nchan):
                off = base + c * row
                got = np.ascontiguousarray(
                    dst[c, :nt]).view(np.uint8).reshape(-1)
                if not np.array_equal(got, mm[off:off + nt * samp]):
                    self._mark_bad(
                        i, "delivered a frame that does not match the "
                           "bytes on disk (in-flight corruption)")
                    return False
            return True
        finally:
            from blit import integrity

            integrity.observe_verify(time.perf_counter() - t_start)

    def _verify_delivery(self, i: int, dst: np.ndarray, t0: int,
                         nt: int) -> None:
        """The one masking rule both read paths share: a block that is
        already bad, fails its on-disk digest, or delivered bytes that
        do not match disk is ZERO-FILLED in place.

        Masking granularity when a block spans several deliveries:
        ON-DISK rot is detected at the block's FIRST delivery (the
        sidecar check runs before any of its bytes emit), so the whole
        block is zeroed exactly — the zero-filled-oracle identity.  An
        IN-FLIGHT flip is detected at the corrupted delivery; that
        delivery and every later one of the block are zeroed, while
        earlier deliveries already passed the delivered-vs-disk check
        against sidecar-verified disk bytes — they carried CORRECT
        data, never garbage.  ``bad_blocks`` / ``_masked_blocks``
        therefore mean "block contains zero-masked samples"."""
        bad = i in self.bad_blocks or not self._digest_ok(i)
        if not bad and not self._delivery_ok(i, dst, t0, nt):
            bad = True
        if bad:
            dst[:, :nt] = 0

    def read_block(self, i: int) -> np.ndarray:
        """Raw int8 voltages of block ``i``, shaped
        ``(obsnchan, ntime, npol, 2)`` (last axis = re, im).

        Native path: one threaded read into a fresh buffer.  Fallback: a lazy
        memmap view (pages in on consumption, single-threaded)."""
        nchan, ntime, npol = self._block_geometry(i)
        shape = (nchan, ntime, npol, 2)

        def _read():
            act = faults.fire("guppi.read", key=self.path)
            if self.native:
                from blit.io.native import guppi_pread

                nbytes = nchan * ntime * npol * 2
                buf = guppi_pread(self.path, self._data_offsets[i], nbytes)
                arr = buf.view(np.int8).reshape(shape)
            else:
                arr = np.memmap(
                    self.path,
                    dtype=np.int8,
                    mode="r",
                    offset=self._data_offsets[i],
                    shape=shape,
                )
            if act is not None:  # destructive drills apply here too
                if act.mode == "truncate":
                    arr = arr[:, : max(
                        0, ntime - (act.amount or max(1, ntime // 2)))]
                elif act.mode == "corrupt":
                    arr = np.array(arr)  # memmaps are read-only views
                    arr[0] ^= 0x55
            if self._block_digests is not None and arr.shape[1] == ntime:
                # Digest-armed whole-block delivery: verify against the
                # sidecar/disk and deliver zeros on mismatch (masked).
                bad = i in self.bad_blocks or not self._digest_ok(i)
                if (not bad and i < len(self._block_digests)
                        and (self.native or act is not None)):
                    # Only a COPIED frame (native pread buffer, or a
                    # drilled act) can diverge from the disk bytes
                    # _digest_ok just verified — the untouched memmap
                    # view IS those bytes, a second pass proves
                    # nothing.  memcmp, not a digest (the
                    # _delivery_ok rule): the disk already verified,
                    # so equality IS correctness.
                    from blit import integrity

                    off = self._data_offsets[i]
                    t_start = time.perf_counter()
                    same = np.array_equal(
                        np.ascontiguousarray(arr).view(
                            np.uint8).reshape(-1),
                        self._vmap()[off:off + arr.nbytes])
                    integrity.observe_verify(
                        time.perf_counter() - t_start)
                    if not same:
                        self._mark_bad(
                            i, "delivered a frame that does not match "
                               "the bytes on disk (in-flight "
                               "corruption)")
                        bad = True
                if bad:
                    arr = np.zeros(shape, np.int8)
            return arr

        return faults.retry_io(_read, describe=f"guppi read {self.path}")

    def read_block_into(
        self, i: int, dst: np.ndarray, t0: int = 0, ntime_keep: int = -1
    ) -> int:
        """Read samples ``[t0, t0+ntime_keep)`` of every channel of block
        ``i`` directly into ``dst[:, :ntime_keep]`` — the zero-intermediate-
        copy feed for the streaming ring buffer (blit/pipeline.py).

        ``dst``: int8 ``(nchan, >=ntime_keep, npol, 2)`` with C-contiguous
        rows (a time-slice view of a C-contiguous ring buffer qualifies).
        ``ntime_keep=-1`` means through the end of the block.  Returns the
        samples written — callers MUST treat a short return as a hard
        failure (a truncated recording); it is never silently padded.
        Uses the native strided pread when built, else a memmap copy.

        Transient ``OSError``\\ s retry under ``blit.faults.io_policy()``;
        the ``guppi.read`` injection point fires inside the retry loop, so
        injected transients exercise exactly the production recovery path
        (``truncate`` rules shorten the read, ``corrupt`` rules bit-flip
        the delivered frame).
        """
        nchan, ntime, npol = self._block_geometry(i)
        if ntime_keep < 0:
            ntime_keep = ntime - t0
        if t0 < 0 or t0 + ntime_keep > ntime:
            raise ValueError(
                f"read_block_into: [{t0}, {t0 + ntime_keep}) outside block "
                f"of {ntime} samples"
            )
        if dst.dtype != np.int8 or dst.shape[0] != nchan or dst.shape[2:] != (npol, 2):
            raise ValueError("read_block_into: dst shape/dtype mismatch")
        if ntime_keep == 0:
            return 0
        samp_bytes = npol * 2

        def _read() -> int:
            act = faults.fire("guppi.read", key=self.path)
            nt = ntime_keep
            if act is not None and act.mode == "truncate":
                nt = max(0, nt - (act.amount or max(1, nt // 2)))
            if nt:
                if self.native and dst[0].flags.c_contiguous:
                    from blit.io.native import guppi_pread_strided

                    guppi_pread_strided(
                        self.path,
                        self._data_offsets[i] + t0 * samp_bytes,
                        nchan,
                        nt * samp_bytes,
                        ntime * samp_bytes,
                        dst,
                        dst.strides[0],
                    )
                elif dst[0].flags.c_contiguous and hasattr(os, "preadv"):
                    # Pure-python readinto fast path (ISSUE 8): positional
                    # pread of each channel row STRAIGHT into the staging
                    # slab — no mmap setup/teardown per block, no
                    # page-fault-driven copy, one syscall per channel.
                    # The persistent fd is positionless (pread), so the
                    # producer thread needs no seek locking.  preadv is
                    # POSIX-but-not-macOS; platforms without it take the
                    # memmap leg below.
                    self._pread_rows(
                        dst, self._data_offsets[i] + t0 * samp_bytes,
                        nchan, nt * samp_bytes, ntime * samp_bytes,
                    )
                else:
                    mm = np.memmap(
                        self.path,
                        dtype=np.int8,
                        mode="r",
                        offset=self._data_offsets[i],
                        shape=(nchan, ntime, npol, 2),
                    )
                    dst[:, :nt] = mm[:, t0 : t0 + nt]
                if act is not None and act.mode == "corrupt":
                    dst[0, :nt] ^= 0x55
                if self._block_digests is not None:
                    # Digest-armed delivery (ISSUE 13): a block that
                    # fails verification is delivered ZERO-FILLED — the
                    # zero-weight mask, not garbage.
                    self._verify_delivery(i, dst, t0, nt)
            return nt

        return faults.retry_io(_read, describe=f"guppi read {self.path}")

    def _pread_rows(self, dst: np.ndarray, base_off: int, nchan: int,
                    row_bytes: int, row_stride: int) -> None:
        """pread ``row_bytes`` of each of ``nchan`` on-disk channel rows
        (``row_stride`` apart, starting at ``base_off``) into
        ``dst[c, :]``'s contiguous storage (the readinto leg of
        :meth:`read_block_into`)."""
        fd = self._pread_fd
        if fd is None:
            fd = self._pread_fd = os.open(self.path, os.O_RDONLY)
        for c in range(nchan):
            view = memoryview(dst[c]).cast("B")[:row_bytes]
            off = base_off + c * row_stride
            done = 0
            while done < row_bytes:
                # A single preadv is capped (~2 GiB on Linux) and may
                # legally return short — loop until the row is complete;
                # only a zero return (EOF) means the file really ends
                # mid-row.
                got = os.preadv(fd, [view[done:]], off + done)
                if got <= 0:
                    # EOF mid-row is DETERMINISTIC (a truncated file
                    # re-reads identically) — raise a non-OSError so
                    # faults.transient_io doesn't burn the retry/backoff
                    # budget re-reading it.
                    raise EOFError(
                        f"{self.path}: short pread ({done} of "
                        f"{row_bytes} bytes at offset {off}) — "
                        "truncated recording?"
                    )
                done += got

    def close(self) -> None:
        """Release the persistent pread descriptor and the verification
        memmap (idempotent; the reader stays usable — both reopen on
        demand)."""
        fd, self._pread_fd = self._pread_fd, None
        if fd is not None:
            os.close(fd)
        self._verify_map = None

    def __del__(self):  # best-effort: interpreter teardown tolerant
        try:
            self.close()
        except Exception:  # noqa: BLE001
            pass

    def read_block_complex(self, i: int) -> np.ndarray:
        """Block ``i`` as complex64, shaped ``(obsnchan, ntime, npol)``."""
        b = self.read_block(i).astype(np.float32)
        return (b[..., 0] + 1j * b[..., 1]).astype(np.complex64)


def scan_files(stem_or_path: str) -> List[str]:
    """Expand one member (or the bare stem) of a ``.NNNN.raw`` sequence into
    the full sorted sequence present on disk.

    ``"x.0001.raw"`` and ``"x"`` both yield ``["x.0000.raw", "x.0001.raw",
    ...]``.  NNNN is zero-padded, so lexical sort is numeric sort.  Returns
    ``[]`` when nothing matches.
    """
    m = SEQ_RE.match(stem_or_path)
    stem = m.group("stem") if m else stem_or_path
    return sorted(glob.glob(glob.escape(stem) + ".[0-9][0-9][0-9][0-9].raw"))


class GuppiScan(_BlockStream):
    """A multi-file GUPPI RAW scan sequence as one gap-free block stream.

    Presents the same indexed-block API as :class:`GuppiRaw` (``nblocks``,
    ``header``, ``read_block_into`` ...), with the file boundaries erased:
    the trailing ``OVERLAP`` samples of the last block of every file but the
    final one repeat at the start of the next file, exactly as they do
    between blocks within a file, so ``block_ntime_kept`` trims them — the
    streaming reducer's PFB state then carries across files for free.

    rawspec (the tool being replaced) always consumes the whole sequence;
    the reference's grammar records the NNNN field but its RAW path stops at
    inventory (src/gbtworkerfunctions.jl:35-47).

    ``strict=True`` turns sequence-consistency findings (missing NNNN in the
    stem sequence, PKTIDX discontinuity or non-monotonicity at a file
    boundary — all meaning dropped samples) into errors.  The exact
    continuity check needs the per-block packet stride, learned from
    within-file deltas; when no unambiguous stride exists (single-block
    files, mixed block sizes) the boundary check degrades to
    strictly-increasing PKTIDX.
    """

    def __init__(
        self,
        paths: Sequence[str],
        native: Optional[bool] = None,
        strict: bool = False,
    ):
        if not paths:
            raise ValueError("GuppiScan: empty path sequence")
        self.paths = list(paths)
        self.files = [GuppiRaw(p, native=native) for p in self.paths]
        empties = [f.path for f in self.files if f.nblocks == 0]
        if empties:
            raise ValueError(f"empty or fully truncated RAW file(s): {empties}")
        self.path = self.paths[0]  # logging/error identity
        self.native = self.files[0].native
        # Flattened (file, local block) index.
        self._blocks: List[Tuple[int, int]] = [
            (fi, bi)
            for fi, f in enumerate(self.files)
            for bi in range(f.nblocks)
        ]
        self._check_sequence(strict)
        # Geometry must agree across files (one recording, one config).
        g0 = self.files[0]._block_geometry(0)
        for f in self.files[1:]:
            g = f._block_geometry(0)
            if (g[0], g[2]) != (g0[0], g0[2]):
                raise ValueError(
                    f"{f.path}: (nchan, npol)={g[0], g[2]} disagrees with "
                    f"{self.path}'s {g0[0], g0[2]}"
                )

    def _check_sequence(self, strict: bool) -> None:
        problems = []
        # A member listed twice would silently splice the same voltages
        # into the stream twice (a "longer" recording of wrong data) —
        # catch it on the raw path list, grammar or not.  Paths are
        # realpath-normalized so alias spellings (./x vs x, symlinks) of
        # one local file cannot dodge the check; unlike the inventory
        # layer, this list names files on THIS host, so resolving is safe.
        real = [os.path.realpath(p) for p in self.paths]
        if len(set(real)) != len(real):
            dups = sorted({p for p, r in zip(self.paths, real)
                           if real.count(r) > 1})
            problems.append(f"duplicate member paths: {dups}")
        # Stem / NNNN continuity (when the names follow the grammar).
        parsed = [SEQ_RE.match(p) for p in self.paths]
        if all(parsed) and len({m.group("stem") for m in parsed}) == 1:
            seqs = [int(m.group("seq")) for m in parsed]
            if seqs != sorted(seqs):
                problems.append(f"sequence numbers out of order: {seqs}")
            missing = sorted(set(range(seqs[0], seqs[-1] + 1)) - set(seqs))
            if missing:
                problems.append(f"missing sequence numbers: {missing}")
        # PKTIDX continuity across file boundaries: within-file block deltas
        # establish the per-block packet stride; a different stride at a
        # boundary means dropped blocks (a gap the PFB must not integrate
        # across).  Real PKTIDX counts packets, not samples, so the stride is
        # learned from the data rather than derived from headers.  With no
        # unambiguous stride (single-block files, mixed block sizes) the
        # check degrades to strictly-increasing — weaker, but never silently
        # skipped.
        strides = set()
        for f in self.files:
            idxs = [h.get("PKTIDX") for h in f.headers]
            for a, b in zip(idxs, idxs[1:]):
                if a is not None and b is not None:
                    strides.add(b - a)
        stride = strides.pop() if len(strides) == 1 else None
        for k in range(len(self.files) - 1):
            last = self.files[k].headers[-1].get("PKTIDX")
            first = self.files[k + 1].headers[0].get("PKTIDX")
            if last is None or first is None:
                continue
            if stride is not None and first - last != stride:
                problems.append(
                    f"PKTIDX gap at {self.paths[k + 1]}: expected "
                    f"{last + stride}, got {first}"
                )
            elif stride is None and first <= last:
                problems.append(
                    f"PKTIDX not increasing at {self.paths[k + 1]}: "
                    f"{last} -> {first}"
                )
        for p in problems:
            if strict:
                raise ValueError(f"GuppiScan: {p}")
            log.warning("GuppiScan: %s", p)

    @property
    def nblocks(self) -> int:
        return len(self._blocks)

    def header(self, i: int = 0) -> Dict:
        fi, bi = self._blocks[i]
        return self.files[fi].headers[bi]

    def _block_geometry(self, i: int) -> Tuple[int, int, int]:
        fi, bi = self._blocks[i]
        return self.files[fi]._block_geometry(bi)

    def read_block(self, i: int) -> np.ndarray:
        fi, bi = self._blocks[i]
        return self.files[fi].read_block(bi)

    def read_block_into(
        self, i: int, dst: np.ndarray, t0: int = 0, ntime_keep: int = -1
    ) -> int:
        fi, bi = self._blocks[i]
        return self.files[fi].read_block_into(bi, dst, t0=t0, ntime_keep=ntime_keep)

    def read_block_complex(self, i: int) -> np.ndarray:
        fi, bi = self._blocks[i]
        return self.files[fi].read_block_complex(bi)

    @property
    def bad_blocks(self) -> set:
        """Digest-failed (masked) blocks as GLOBAL stream indices —
        the union of every member's per-file mask set (ISSUE 13)."""
        return {
            g for g, (fi, bi) in enumerate(self._blocks)
            if bi in self.files[fi].bad_blocks
        }


RawSource = Union[str, Sequence[str], GuppiRaw, GuppiScan]


def open_raw(src: RawSource, native: Optional[bool] = None):
    """Open a RAW source as a block stream: a :class:`GuppiRaw` /
    :class:`GuppiScan` passes through; a path list becomes a scan; a single
    path opens that file; a *stem* (no such file on disk, but
    ``<stem>.NNNN.raw`` members exist) expands to the whole sequence.
    """
    if isinstance(src, (GuppiRaw, GuppiScan)):
        return src
    if isinstance(src, (list, tuple)):
        if len(src) == 1:
            return GuppiRaw(src[0], native=native)
        return GuppiScan(src, native=native)
    if os.path.exists(src):
        return GuppiRaw(src, native=native)
    seq = scan_files(src)
    if not seq:
        raise FileNotFoundError(f"no RAW file or .NNNN.raw sequence at {src!r}")
    if len(seq) == 1:
        return GuppiRaw(seq[0], native=native)
    return GuppiScan(seq, native=native)


def write_raw(
    path: str,
    header: Dict,
    blocks: List[np.ndarray],
    directio: bool = False,
) -> None:
    """Write a GUPPI RAW file (fixture generator and pipeline output).

    ``blocks``: int8 arrays shaped ``(obsnchan, ntime, npol, 2)``.  Per-block
    headers are derived from ``header`` with ``BLOCSIZE``/``PKTIDX`` updated.
    """
    hdr = dict(header)
    hdr["DIRECTIO"] = 1 if directio else 0
    pktidx = int(hdr.get("PKTIDX", 0))
    with open(path, "wb") as f:
        for blk in blocks:
            if blk.dtype != np.int8 or blk.ndim != 4 or blk.shape[3] != 2:
                raise ValueError("write_raw: blocks must be int8 (nchan, ntime, npol, 2)")
            nchan, ntime, npol, _ = blk.shape
            hdr["OBSNCHAN"] = nchan
            hdr["NPOL"] = 4 if npol == 2 else npol
            hdr["NBITS"] = 8
            hdr["BLOCSIZE"] = blk.nbytes
            hdr["PKTIDX"] = pktidx
            pktidx += ntime - int(hdr.get("OVERLAP", 0))
            cards = b"".join(_format_card(k, v) for k, v in hdr.items())
            cards += "END".ljust(CARD_LEN).encode("ascii")
            f.write(cards)
            if directio:
                f.write(b"\x00" * ((-len(cards)) % DIRECTIO_ALIGN))
            f.write(np.ascontiguousarray(blk).tobytes())
