"""Loader for blit's native (C++) acceleration libraries.

SURVEY.md §2.3: the reference's native surface lives in its dependencies —
the bitshuffle HDF5 filter (C/SSE2/AVX2) and Blio's block readers.  blit
provides C++ equivalents under ``blit/native/``; this module locates the
built artifacts and degrades gracefully (NumPy fallbacks) when absent.
"""

from __future__ import annotations

import ctypes
import os
from typing import Optional

_NATIVE_DIR = os.path.join(os.path.dirname(__file__), "..", "native", "build")


def native_dir() -> str:
    return os.path.abspath(_NATIVE_DIR)


def lib_path(name: str) -> Optional[str]:
    p = os.path.join(native_dir(), name)
    return p if os.path.exists(p) else None


_guppi_lib = None


def guppi_lib() -> Optional[ctypes.CDLL]:
    """ctypes handle to the C++ GUPPI block reader, or None if not built."""
    global _guppi_lib
    if _guppi_lib is not None:
        return _guppi_lib
    p = lib_path("libblit_guppi.so")
    if p is None:
        return None
    lib = ctypes.CDLL(p)
    lib.blit_guppi_pread.restype = ctypes.c_int
    lib.blit_guppi_pread.argtypes = [
        ctypes.c_char_p,
        ctypes.c_uint64,
        ctypes.c_uint64,
        ctypes.c_void_p,
        ctypes.c_int,
    ]
    if not hasattr(lib, "blit_guppi_pread2"):
        return None  # stale build; rebuild with make -C blit/native
    lib.blit_guppi_pread2.restype = ctypes.c_int
    lib.blit_guppi_pread2.argtypes = [
        ctypes.c_char_p,
        ctypes.c_uint64,
        ctypes.c_uint64,
        ctypes.c_uint64,
        ctypes.c_uint64,
        ctypes.c_uint64,
        ctypes.c_void_p,
        ctypes.c_int,
    ]
    _guppi_lib = lib
    return _guppi_lib


def guppi_pread_strided(
    path: str,
    offset: int,
    nchan: int,
    chan_bytes: int,
    src_stride: int,
    dst,
    dst_stride: int,
    nthreads: int = 8,
) -> None:
    """Threaded strided read: channel ``c``'s bytes ``[offset +
    c*src_stride, +chan_bytes)`` land at ``dst + c*dst_stride`` — the
    zero-copy feed from a GUPPI block on disk into the streaming ring
    buffer (blit/native/guppi.cc).  ``dst``: a C-contiguous ndarray whose
    buffer the rows fit inside.  Raises ``OSError`` on failure;
    ``RuntimeError`` if the library is unbuilt."""
    lib = guppi_lib()
    if lib is None:
        raise RuntimeError("native GUPPI reader unbuilt: make -C blit/native")
    try:  # numpy 2.x home, 1.x fallback
        from numpy.lib.array_utils import byte_bounds
    except ImportError:  # pragma: no cover
        from numpy import byte_bounds
    low, high = byte_bounds(dst)
    base = dst.ctypes.data
    if base < low or base + dst_stride * (nchan - 1) + chan_bytes > high:
        raise ValueError("guppi_pread_strided: rows exceed dst buffer")
    rc = lib.blit_guppi_pread2(
        path.encode(), offset, nchan, chan_bytes, src_stride, dst_stride,
        base, nthreads,
    )
    if rc:
        import os as _os

        raise OSError(-rc, _os.strerror(-rc), path)


def guppi_pread(path: str, offset: int, size: int, nthreads: int = 8):
    """Threaded pread of ``[offset, offset+size)`` into a fresh uint8 array
    via the native reader (blit/native/guppi.cc).  Raises ``OSError`` on
    failure; ``RuntimeError`` if the library is unbuilt."""
    import numpy as np

    lib = guppi_lib()
    if lib is None:
        raise RuntimeError("native GUPPI reader unbuilt: make -C blit/native")
    out = np.empty(size, np.uint8)
    rc = lib.blit_guppi_pread(
        path.encode(), offset, size, out.ctypes.data, nthreads
    )
    if rc:
        import os as _os

        raise OSError(-rc, _os.strerror(-rc), path)
    return out
