"""Loader for blit's native (C++) acceleration libraries.

SURVEY.md §2.3: the reference's native surface lives in its dependencies —
the bitshuffle HDF5 filter (C/SSE2/AVX2) and Blio's block readers.  blit
provides C++ equivalents under ``blit/native/``; this module locates the
built artifacts and degrades gracefully (NumPy fallbacks) when absent.
"""

from __future__ import annotations

import ctypes
import os
from typing import Optional

_NATIVE_DIR = os.path.join(os.path.dirname(__file__), "..", "native", "build")
_plugin_registered = False


def native_dir() -> str:
    return os.path.abspath(_NATIVE_DIR)


def lib_path(name: str) -> Optional[str]:
    p = os.path.join(native_dir(), name)
    return p if os.path.exists(p) else None


def ensure_hdf5_plugin_path() -> bool:
    """Make libhdf5 see blit's filter plugins (bitshuffle+LZ4).

    Must run before the first h5py File open that needs the filter.  Uses the
    HDF5 plugin-path API via h5py so it works even after HDF5_PLUGIN_PATH has
    been read at library init.
    """
    global _plugin_registered
    if _plugin_registered:
        return True
    d = native_dir()
    if not os.path.isdir(d) or not any(
        f.startswith("libblit_h5bshuf") for f in os.listdir(d)
    ):
        return False
    try:
        import h5py

        h5py.h5pl.prepend(d.encode())
        _plugin_registered = True
        return True
    except Exception:
        return False


_guppi_lib = None


def guppi_lib() -> Optional[ctypes.CDLL]:
    """ctypes handle to the C++ GUPPI block reader, or None if not built."""
    global _guppi_lib
    if _guppi_lib is not None:
        return _guppi_lib
    p = lib_path("libblit_guppi.so")
    if p is None:
        return None
    _guppi_lib = ctypes.CDLL(p)
    return _guppi_lib
