"""Python bindings for the native bitshuffle+LZ4 codec (blit/native/
bitshuffle.cc) — the replacement for the reference's H5Zbitshuffle.jl
dependency (SURVEY.md §2.2-2.3).

Used by :mod:`blit.io.fbh5` for direct-chunk FBH5 compression: chunks carry
HDF5 filter id 32008 in the dataset's filter pipeline (so external tools
with the standard bitshuffle plugin read our files), while blit itself
encodes/decodes chunks through this codec and h5py's
``read_direct_chunk``/``write_direct_chunk`` — no HDF5 plugin machinery
needed.
"""

from __future__ import annotations

import ctypes
from typing import Optional

import numpy as np

from blit.io.native import lib_path

BITSHUFFLE_FILTER_ID = 32008
H5_COMPRESS_LZ4 = 2
# (major, minor) the upstream filter stamps into cd_values.
_FILTER_VERSION = (0, 4)

_lib: Optional[ctypes.CDLL] = None
_lib_missing = False


def _load() -> Optional[ctypes.CDLL]:
    global _lib, _lib_missing
    if _lib is not None or _lib_missing:
        return _lib
    p = lib_path("libblit_bshuf.so")
    if p is None:
        _lib_missing = True
        return None
    lib = ctypes.CDLL(p)
    lib.blit_bshuf_default_block_size.restype = ctypes.c_size_t
    lib.blit_bshuf_default_block_size.argtypes = [ctypes.c_size_t]
    lib.blit_bshuf_shuffle.restype = ctypes.c_int
    lib.blit_bshuf_shuffle.argtypes = [ctypes.c_void_p] * 2 + [ctypes.c_size_t] * 2
    lib.blit_bshuf_unshuffle.restype = ctypes.c_int
    lib.blit_bshuf_unshuffle.argtypes = [ctypes.c_void_p] * 2 + [ctypes.c_size_t] * 2
    lib.blit_bshuf_compress_bound.restype = ctypes.c_int64
    lib.blit_bshuf_compress_bound.argtypes = [ctypes.c_size_t] * 3
    lib.blit_bshuf_compress_lz4.restype = ctypes.c_int64
    lib.blit_bshuf_compress_lz4.argtypes = [
        ctypes.c_void_p, ctypes.c_void_p, ctypes.c_size_t, ctypes.c_size_t,
        ctypes.c_size_t,
    ]
    lib.blit_bshuf_decompress_lz4.restype = ctypes.c_int64
    lib.blit_bshuf_decompress_lz4.argtypes = [
        ctypes.c_void_p, ctypes.c_size_t, ctypes.c_void_p, ctypes.c_size_t,
        ctypes.c_size_t,
    ]
    _lib = lib
    return _lib


def available() -> bool:
    """True when the native codec library is built and loadable."""
    return _load() is not None


def default_block_size(elem_size: int) -> int:
    lib = _load()
    if lib is None:
        raise RuntimeError("bitshuffle codec unavailable: build blit/native")
    return lib.blit_bshuf_default_block_size(elem_size)


def bitshuffle(a: np.ndarray) -> np.ndarray:
    """Bit-transpose (no compression) — element count must be a multiple of
    8.  Exposed mainly for tests against the NumPy model."""
    lib = _load()
    if lib is None:
        raise RuntimeError("bitshuffle codec unavailable: build blit/native")
    a = np.ascontiguousarray(a)
    out = np.empty(a.nbytes, np.uint8)
    rc = lib.blit_bshuf_shuffle(
        a.ctypes.data, out.ctypes.data, a.size, a.itemsize
    )
    if rc:
        raise ValueError(f"bitshuffle failed (rc={rc}); size must be 8k")
    return out


def bitunshuffle(buf: np.ndarray, dtype, count: int) -> np.ndarray:
    lib = _load()
    if lib is None:
        raise RuntimeError("bitshuffle codec unavailable: build blit/native")
    dtype = np.dtype(dtype)
    buf = np.ascontiguousarray(np.frombuffer(buf, np.uint8))
    if buf.size != count * dtype.itemsize:
        raise ValueError(
            f"bitunshuffle: buffer holds {buf.size} bytes, "
            f"need exactly {count * dtype.itemsize}"
        )
    out = np.empty(count, dtype)
    rc = lib.blit_bshuf_unshuffle(
        buf.ctypes.data, out.ctypes.data, count, dtype.itemsize
    )
    if rc:
        raise ValueError(f"bitunshuffle failed (rc={rc})")
    return out


def compress_chunk(a: np.ndarray, block_size: int = 0) -> bytes:
    """Encode one HDF5 chunk's worth of data into the bitshuffle-LZ4 wire
    format (the exact payload ``write_direct_chunk`` stores)."""
    lib = _load()
    if lib is None:
        raise RuntimeError("bitshuffle codec unavailable: build blit/native")
    a = np.ascontiguousarray(a)
    bound = lib.blit_bshuf_compress_bound(a.size, a.itemsize, block_size)
    out = np.empty(bound, np.uint8)
    n = lib.blit_bshuf_compress_lz4(
        a.ctypes.data, out.ctypes.data, a.size, a.itemsize, block_size
    )
    if n < 0:
        raise ValueError(f"bitshuffle compress failed (rc={n})")
    return out[:n].tobytes()


def decompress_chunk(payload: bytes, dtype, count: int) -> np.ndarray:
    """Decode one chunk payload back to ``count`` elements of ``dtype``."""
    lib = _load()
    if lib is None:
        raise RuntimeError("bitshuffle codec unavailable: build blit/native")
    dtype = np.dtype(dtype)
    src = np.frombuffer(payload, np.uint8)
    out = np.empty(count, dtype)
    n = lib.blit_bshuf_decompress_lz4(
        src.ctypes.data, len(payload), out.ctypes.data, count, dtype.itemsize
    )
    if n < 0:
        raise ValueError(f"bitshuffle decompress failed (rc={n})")
    return out


def filter_cd_values(elem_size: int, block_size: int = 0) -> tuple:
    """cd_values stamped into the HDF5 filter pipeline, matching the
    upstream bitshuffle plugin's convention."""
    return (
        _FILTER_VERSION[0],
        _FILTER_VERSION[1],
        elem_size,
        block_size,
        H5_COMPRESS_LZ4,
    )


# -- NumPy model (golden reference for the C++ bit transpose) -------------


def bitshuffle_np(a: np.ndarray) -> np.ndarray:
    """Pure-NumPy bitshuffle model: out row (byte_pos*8 + bit), bit 0 = LSB;
    within a row, bit j of byte i belongs to element 8i+j."""
    a = np.ascontiguousarray(a)
    nelem, elem_size = a.size, a.itemsize
    if nelem % 8:
        raise ValueError("element count must be a multiple of 8")
    by = a.view(np.uint8).reshape(nelem, elem_size)  # [elem][byte]
    # bits[e, b, k] = bit k (LSB-first) of byte b of element e
    bits = (by[:, :, None] >> np.arange(8)) & 1
    # target layout: rows [byte_pos][bit], columns element; bit j of out byte
    # i = element 8i+j → packbits with bitorder little over the element axis.
    rows = bits.transpose(1, 2, 0).reshape(elem_size * 8, nelem)
    return np.packbits(rows, axis=-1, bitorder="little").reshape(-1)
