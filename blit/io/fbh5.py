"""FBH5 — HDF5-wrapped filterbank files (``*.h5``).

Replaces HDF5.jl + H5Zbitshuffle.jl usage (reference:
src/gbtworkerfunctions.jl:141-155, 179-189).  An FBH5 file holds one ``data``
dataset shaped ``(nsamps, nifs, nchans)`` whose attributes carry the
filterbank header; BL files are bitshuffle+LZ4 compressed (decoded natively
when ``blit/native``'s HDF5 filter plugin is built, see blit/io/native.py).
"""

from __future__ import annotations

from typing import Dict, Optional, Tuple

import h5py
import numpy as np

from blit.config import nfpc_from_foff
from blit.io import native as _native

BITSHUFFLE_FILTER_ID = 32008  # registered HDF5 filter id for bitshuffle

_native.ensure_hdf5_plugin_path()


def is_hdf5(path: str) -> bool:
    """Format dispatch predicate (reference: ``HDF5.ishdf5``,
    src/gbtworkerfunctions.jl:158)."""
    return h5py.is_hdf5(path)


def _pyvalue(v):
    """Normalize an HDF5 attribute value to a plain Python scalar/str."""
    if isinstance(v, bytes):
        return v.decode("utf-8")
    if isinstance(v, np.ndarray):
        if v.shape == ():
            return _pyvalue(v[()])
        if v.dtype.kind == "S":
            return [x.decode("utf-8") for x in v]
        return v
    if isinstance(v, np.generic):
        return v.item()
    return v


def read_fbh5_header(path: str) -> Dict:
    """All attributes of the ``data`` dataset except ``DIMENSION_LABELS``,
    plus computed ``data_size`` and ``nsamps``, key-sorted.

    Reference: ``getfbh5header`` (src/gbtworkerfunctions.jl:141-155).  The
    reference's missing-``nfpc`` branch crashes on an undefined variable
    (SURVEY.md §2.1 wart list); here it correctly computes ``nfpc`` from the
    ``foff`` attribute when absent.
    """
    with h5py.File(path, "r") as h5:
        data = h5["data"]
        hdr = {
            k: _pyvalue(v)
            for k, v in data.attrs.items()
            if k != "DIMENSION_LABELS"
        }
        if "nfpc" not in hdr and "foff" in hdr:
            hdr["nfpc"] = nfpc_from_foff(hdr["foff"])
        hdr["data_size"] = data.dtype.itemsize * int(np.prod(data.shape))
        # Julia's size(data, ndims) is the slowest-varying (time) axis —
        # C-order shape[0] here.
        hdr["nsamps"] = data.shape[0]
    return dict(sorted(hdr.items()))


def read_fbh5_data(
    path: str, idxs: Optional[Tuple] = None
) -> np.ndarray:
    """Read the ``data`` dataset, full or as a hyperslab.

    ``idxs`` is a 3-tuple of slices over ``(time, pol, chan)``; None or
    all-``slice(None)`` does a single full read (reference distinguishes the
    same two paths: src/gbtworkerfunctions.jl:183-186).  Decompression (gzip
    or bitshuffle, if the plugin is available) happens inside libhdf5 here.
    """
    with h5py.File(path, "r") as h5:
        ds = h5["data"]
        if idxs is not None and len(idxs) != 3:
            raise ValueError("idxs must have exactly three indices")
        if idxs is None or all(i == slice(None) for i in idxs):
            return ds[()]
        return ds[idxs]


def write_fbh5(
    path: str,
    header: Dict,
    data: np.ndarray,
    compression: Optional[str] = None,
    chunks: Optional[Tuple[int, int, int]] = None,
) -> None:
    """Write an FBH5 file: ``data`` dataset + header attributes.

    ``compression``: None | "gzip" | "bitshuffle" (bitshuffle requires the
    native plugin from ``blit/native``; raises if unavailable).
    """
    if data.ndim != 3:
        raise ValueError("write_fbh5: data must be (nsamps, nifs, nchans)")
    kw = {}
    if chunks is not None:
        kw["chunks"] = chunks
    if compression == "gzip":
        kw["compression"] = "gzip"
        kw.setdefault("chunks", True)
    elif compression == "bitshuffle":
        if not h5py.h5z.filter_avail(BITSHUFFLE_FILTER_ID):
            raise RuntimeError(
                "bitshuffle HDF5 filter unavailable; build blit/native first"
            )
        kw["compression"] = BITSHUFFLE_FILTER_ID
        kw["compression_opts"] = (0, 2)  # block size auto, 2 = LZ4
        kw.setdefault("chunks", (min(data.shape[0], 16), data.shape[1], data.shape[2]))
    elif compression is not None:
        raise ValueError(f"unknown compression {compression!r}")

    with h5py.File(path, "w") as h5:
        h5.attrs["CLASS"] = np.bytes_(b"FILTERBANK")
        h5.attrs["VERSION"] = np.bytes_(b"1.0")
        ds = h5.create_dataset("data", data=data, **kw)
        for k, v in header.items():
            if k in ("data_size", "nsamps"):
                continue  # computed on read
            if isinstance(v, str):
                ds.attrs[k] = np.bytes_(v.encode())
            else:
                ds.attrs[k] = v
        ds.attrs["DIMENSION_LABELS"] = np.array(
            [b"time", b"feed_id", b"frequency"], dtype="S9"
        )
