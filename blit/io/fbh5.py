"""FBH5 — HDF5-wrapped filterbank files (``*.h5``).

Replaces HDF5.jl + H5Zbitshuffle.jl usage (reference:
src/gbtworkerfunctions.jl:141-155, 179-189).  An FBH5 file holds one ``data``
dataset shaped ``(nsamps, nifs, nchans)`` whose attributes carry the
filterbank header; BL files are bitshuffle+LZ4 compressed.

Bitshuffle support does not use HDF5's filter-plugin machinery at all:
chunks are encoded/decoded by blit's native C++ codec (blit/io/bshuf.py →
blit/native/bitshuffle.cc) through h5py's direct-chunk I/O, while the
dataset's filter pipeline still carries the standard filter id 32008 so
files interoperate with external tools that have the upstream plugin.
"""

from __future__ import annotations

import os
from typing import Dict, Optional, Tuple

import h5py
import numpy as np

from blit import faults
from blit.config import nfpc_from_foff
from blit.io.bshuf import BITSHUFFLE_FILTER_ID

# libhdf5 refuses chunks of 4 GiB or more (H5Dcreate fails); hi-res blit
# products have 2^20-point spectra, where BL's conventional 16-spectra chunk
# row would be 16 GiB — defaults must clamp, not crash at writer open.
H5_CHUNK_LIMIT = 2**32 - 1


def default_chunks(
    nifs: int,
    nchans: int,
    itemsize: int,
    *,
    whole_spectrum: bool = False,
) -> Tuple[int, int, int]:
    """BL's conventional ``(16, nifs, nchans)`` whole-spectrum chunk rows,
    with the time rows clamped so chunk bytes stay under HDF5's 4 GiB-1
    chunk limit (a hi-res 64-channel-bank Stokes product is 256 MiB per
    spectrum; the full-band IQUV mesh product is 8 GiB per spectrum).

    When even ONE spectrum exceeds the limit the channel axis is split —
    unless ``whole_spectrum=True`` (the streaming bitshuffle writer stores
    one chunk per time row and cannot split channels), which raises
    instead of returning an unusable chunk shape.
    """
    row_bytes = nifs * nchans * itemsize
    rows = max(1, min(16, H5_CHUNK_LIMIT // max(row_bytes, 1)))
    if rows * row_bytes <= H5_CHUNK_LIMIT:
        return (rows, nifs, nchans)
    if whole_spectrum:
        raise ValueError(
            f"one ({nifs}, {nchans}) spectrum is {row_bytes} bytes, over "
            f"HDF5's 4 GiB-1 chunk limit, and this writer needs "
            "whole-spectrum chunks: reduce nchans per product (e.g. "
            "per-band files) or use uncompressed/gzip output"
        )
    return (1, nifs, max(1, H5_CHUNK_LIMIT // (nifs * itemsize)))


def _bitshuffle_cd_values(ds) -> Optional[Tuple]:
    """cd_values if the dataset's filter pipeline contains bitshuffle."""
    try:
        plist = ds.id.get_create_plist()
        for i in range(plist.get_nfilters()):
            code, _flags, cd, _name = plist.get_filter(i)
            if code == BITSHUFFLE_FILTER_ID:
                return tuple(cd)
    except Exception:  # noqa: BLE001 - treat unreadable pipelines as plain
        return None
    return None


def _needs_manual_bitshuffle(ds) -> bool:
    return (
        _bitshuffle_cd_values(ds) is not None
        and not h5py.h5z.filter_avail(BITSHUFFLE_FILTER_ID)
    )


def _read_bitshuffle_chunks(ds, bbox: Tuple[Tuple[int, int], ...]) -> np.ndarray:
    """Assemble the half-open bounding box ``bbox`` of a bitshuffle dataset
    by decoding exactly the intersecting chunks with the native codec.

    Chunk payloads are read serially (libhdf5 is not thread-safe), then
    decoded in a thread pool — the native unshuffle+LZ4 runs GIL-free via
    ctypes, so decode scales with cores instead of serializing behind the
    reads (the libhdf5-filter path the reference uses decodes chunks one at
    a time inside H5Dread)."""
    import itertools
    from concurrent.futures import ThreadPoolExecutor

    from blit.io import bshuf

    if not bshuf.available():
        raise RuntimeError(
            "file needs the bitshuffle codec: build blit/native (make -C blit/native)"
        )
    chunk = ds.chunks
    shape = ds.shape
    out = np.empty([hi - lo for lo, hi in bbox], ds.dtype)
    ranges = [
        range(lo // c * c, hi, c) for (lo, hi), c in zip(bbox, chunk)
    ]

    def place(corner, payload):
        full = tuple(min(c, s - o) for c, s, o in zip(chunk, shape, corner))
        # Chunks are stored at full chunk size (edge chunks padded).
        dec = bshuf.decompress_chunk(
            payload, ds.dtype, int(np.prod(chunk))
        ).reshape(chunk)[tuple(slice(0, f) for f in full)]
        src = tuple(
            slice(max(lo - o, 0), min(hi - o, f))
            for (lo, hi), o, f in zip(bbox, corner, full)
        )
        dst = tuple(
            slice(max(o - lo, 0), max(o - lo, 0) + (s.stop - s.start))
            for (lo, _hi), o, s in zip(bbox, corner, src)
        )
        out[dst] = dec[src]

    corners = list(itertools.product(*ranges))
    if len(corners) == 1:
        place(corners[0], ds.id.read_direct_chunk(corners[0])[1])
        return out
    # Stream: reads stay serial, decodes overlap them in the pool; bounding
    # the in-flight futures bounds how many compressed payloads are resident
    # at once (a whole-file read must not hold the compressed file in RAM).
    from collections import deque

    nthreads = min(len(corners), os.cpu_count() or 1)
    inflight: deque = deque()
    with ThreadPoolExecutor(nthreads) as pool:
        for corner in corners:
            payload = ds.id.read_direct_chunk(corner)[1]
            inflight.append(pool.submit(place, corner, payload))
            while len(inflight) > 2 * nthreads:
                inflight.popleft().result()  # re-raises worker errors
        for f in inflight:
            f.result()
    return out


def is_hdf5(path: str) -> bool:
    """Format dispatch predicate (reference: ``HDF5.ishdf5``,
    src/gbtworkerfunctions.jl:158)."""
    return h5py.is_hdf5(path)


def _pyvalue(v):
    """Normalize an HDF5 attribute value to a plain Python scalar/str."""
    if isinstance(v, bytes):
        return v.decode("utf-8")
    if isinstance(v, np.ndarray):
        if v.shape == ():
            return _pyvalue(v[()])
        if v.dtype.kind == "S":
            return [x.decode("utf-8") for x in v]
        return v
    if isinstance(v, np.generic):
        return v.item()
    return v


def read_fbh5_header(path: str) -> Dict:
    """All attributes of the ``data`` dataset except ``DIMENSION_LABELS``,
    plus computed ``data_size`` and ``nsamps``, key-sorted.

    Reference: ``getfbh5header`` (src/gbtworkerfunctions.jl:141-155).  The
    reference's missing-``nfpc`` branch crashes on an undefined variable
    (SURVEY.md §2.1 wart list); here it correctly computes ``nfpc`` from the
    ``foff`` attribute when absent.
    """
    with h5py.File(path, "r") as h5:
        data = h5["data"]
        hdr = {
            k: _pyvalue(v)
            for k, v in data.attrs.items()
            if k != "DIMENSION_LABELS"
        }
        if "nfpc" not in hdr and "foff" in hdr:
            hdr["nfpc"] = nfpc_from_foff(hdr["foff"])
        hdr["data_size"] = data.dtype.itemsize * int(np.prod(data.shape))
        # Julia's size(data, ndims) is the slowest-varying (time) axis —
        # C-order shape[0] here.
        hdr["nsamps"] = data.shape[0]
    return dict(sorted(hdr.items()))


def read_fbh5_data(
    path: str, idxs: Optional[Tuple] = None
) -> np.ndarray:
    """Read the ``data`` dataset, full or as a hyperslab.

    ``idxs`` is a 3-tuple of slices over ``(time, pol, chan)``; None or
    all-``slice(None)`` does a single full read (reference distinguishes the
    same two paths: src/gbtworkerfunctions.jl:183-186).  Decompression (gzip
    or bitshuffle, if the plugin is available) happens inside libhdf5 here.
    """
    with h5py.File(path, "r") as h5:
        ds = h5["data"]
        if idxs is not None and len(idxs) != 3:
            raise ValueError("idxs must have exactly three indices")
        full = idxs is None or all(i == slice(None) for i in idxs)
        if not _needs_manual_bitshuffle(ds):
            return ds[()] if full else ds[idxs]
        # Manual path: decode intersecting chunks with the native codec.
        if idxs is None:
            idxs = (slice(None),) * 3
        norm = []
        for i, n in zip(idxs, ds.shape):
            if isinstance(i, slice):
                norm.append(i.indices(n))
            else:
                j = int(i) + n if int(i) < 0 else int(i)  # h5py-style negatives
                norm.append((j, j + 1, 1))
        if any(step < 1 or start < 0 for start, _e, step in norm):
            raise ValueError(
                "bitshuffle read: negative steps / out-of-range indices unsupported"
            )
        bbox = tuple((start, max(stop, start)) for start, stop, _ in norm)
        box = _read_bitshuffle_chunks(ds, bbox)
        residual = tuple(
            slice(None, None, step) if isinstance(i, slice) else 0
            for i, (_s, _e, step) in zip(idxs, norm)
        )
        return box[residual]


def _write_bitshuffle_chunks(ds, data: np.ndarray) -> None:
    """Encode every chunk with the native codec and store it via
    direct-chunk writes (edge chunks zero-padded to full chunk size, as the
    upstream filter does)."""
    import itertools

    from blit.io import bshuf

    chunk = ds.chunks
    ranges = [range(0, s, c) for s, c in zip(data.shape, chunk)]
    for corner in itertools.product(*ranges):
        sl = tuple(
            slice(o, min(o + c, s)) for o, c, s in zip(corner, chunk, data.shape)
        )
        block = data[sl]
        if block.shape != chunk:
            padded = np.zeros(chunk, data.dtype)
            padded[tuple(slice(0, b) for b in block.shape)] = block
            block = padded
        ds.id.write_direct_chunk(corner, bshuf.compress_chunk(block))


def _header_attrs(ds, header: Dict) -> None:
    """Stamp the filterbank header onto the ``data`` dataset (shared by the
    whole-array and streaming writers; ``data_size``/``nsamps`` are computed
    on read from the dataset itself)."""
    for k, v in header.items():
        if k in ("data_size", "nsamps"):
            continue  # computed on read
        if isinstance(v, str):
            ds.attrs[k] = np.bytes_(v.encode())
        else:
            ds.attrs[k] = v
    ds.attrs["DIMENSION_LABELS"] = np.array(
        [b"time", b"feed_id", b"frequency"], dtype="S9"
    )


def _compression_kwargs(
    compression: Optional[str], itemsize: int
) -> Tuple[dict, bool]:
    """``h5py.create_dataset`` kwargs for a product codec → ``(kwargs,
    is_bitshuffle)``.  Shared by every FBH5 writer so codec wiring lives
    in one place."""
    if compression == "gzip":
        return {"compression": "gzip"}, False
    if compression == "bitshuffle":
        from blit.io import bshuf

        if not bshuf.available():
            raise RuntimeError(
                "bitshuffle codec unavailable; build blit/native first"
            )
        return {
            "compression": BITSHUFFLE_FILTER_ID,
            "compression_opts": bshuf.filter_cd_values(itemsize),
            "allow_unknown_filter": True,
        }, True
    if compression is not None:
        raise ValueError(f"unknown compression {compression!r}")
    return {}, False


def _stream_chunks(
    chunks: Optional[Tuple[int, int, int]],
    nifs: int,
    nchans: int,
    itemsize: int,
    bitshuffle: bool,
) -> Tuple[int, int, int]:
    """Resolve a streaming writer's chunk shape: explicit or clamped
    default, with the whole-spectrum constraint the streaming bitshuffle
    encoder needs (it stores one chunk per time-row corner; channel-split
    chunks would silently drop data)."""
    c = (
        tuple(chunks)
        if chunks
        else default_chunks(nifs, nchans, itemsize,
                            whole_spectrum=bitshuffle)
    )
    if bitshuffle and c[1:] != (nifs, nchans):
        raise ValueError(
            "bitshuffle streaming needs whole-spectrum chunks: "
            f"chunks[1:] must be ({nifs}, {nchans}), got {c}"
        )
    return c


class _ChunkStream:
    """The bitshuffle chunk-row streaming engine shared by
    :class:`FBH5Writer` and :class:`ResumableFBH5Writer` (state used:
    ``_ds``, ``chunks``, ``dtype``, ``nsamps``, ``_buf``, ``_buffered``).
    Encodes with the native codec and stores via direct-chunk writes,
    buffering at most one chunk row of pending spectra."""

    def _flush_chunk(self, rows: int) -> None:
        """Encode + store the buffered rows as one full chunk (edge chunks
        zero-padded to full chunk size, as the upstream filter does)."""
        from blit.io import bshuf

        if rows < self.chunks[0]:
            self._buf[rows:] = 0
        corner = (self.nsamps, 0, 0)
        payload = bshuf.compress_chunk(self._buf)

        def _write():
            # Idempotent under retry: resize targets an absolute size and
            # the direct-chunk write lands at a fixed corner.
            faults.fire("fbh5.write", key=self.path)
            self._ds.resize(self.nsamps + rows, axis=0)
            self._ds.id.write_direct_chunk(corner, payload)

        faults.retry_io(_write, describe=f"fbh5 chunk write {self.path}")
        self.nsamps += rows
        self._buffered = 0
        # Manifest fold at CLAIM granularity (ISSUE 13): only rows
        # flushed as full chunks are ever claimed by a cursor, so the
        # digest ledger advances exactly with them.
        mf = getattr(self, "_mf", None)
        if mf is not None:
            mf.fold(np.ascontiguousarray(self._buf[:rows]))
            mf.claim(self.nsamps)

    def _buffer_slab(self, slab: np.ndarray) -> bool:
        """Buffer ``slab``'s rows, flushing every completed chunk; returns
        whether at least one chunk was flushed (the durable-progress
        signal the resumable writer checkpoints on)."""
        slab = np.ascontiguousarray(slab, self.dtype)
        ct = self.chunks[0]
        pos, flushed = 0, False
        while pos < slab.shape[0]:
            take = min(ct - self._buffered, slab.shape[0] - pos)
            self._buf[self._buffered:self._buffered + take] = (
                slab[pos:pos + take]
            )
            self._buffered += take
            pos += take
            if self._buffered == ct:
                self._flush_chunk(ct)
                flushed = True
        return flushed


class FBH5Writer(_ChunkStream):
    """Streaming FBH5 product writer: append ``(k, nifs, nchans)`` slabs
    into a time-resizable ``data`` dataset at bounded host memory — the
    ``.h5`` analog of ``RawReducer.reduce_to_file``'s slab-streamed ``.fil``
    path (VERDICT r3 item 5: a hi-res product of a long scan must be
    writable as FBH5, BL's native product format
    (src/gbtworkerfunctions.jl:141-155), without materializing it).

    Peak residency is one chunk row (``chunks[0]`` spectra) plus one
    encoded chunk, regardless of scan length.  Bitshuffle chunks are
    encoded by the native codec and stored via direct-chunk writes exactly
    as :func:`write_fbh5` does, so a streamed file decodes identically to
    an in-memory write of the same data.

    Atomicity mirrors the ``.fil`` streaming writer: bytes land in a
    ``.partial`` sibling and rename onto ``path`` only on a successful
    :meth:`close` — a crash mid-stream must not leave a valid-looking
    truncated product.  Use as a context manager; an exception inside the
    ``with`` removes the partial.
    """

    def __init__(
        self,
        path: str,
        header: Dict,
        *,
        nifs: int,
        nchans: int,
        dtype=np.float32,
        compression: Optional[str] = None,
        chunks: Optional[Tuple[int, int, int]] = None,
    ):
        self.final_path = path
        self.path = path + ".partial"
        self.dtype = np.dtype(dtype)
        kw, self._bitshuffle = _compression_kwargs(
            compression, self.dtype.itemsize
        )
        # A time-resizable dataset must be chunked; default matches
        # write_fbh5's BL convention (16-spectra rows, whole channel span),
        # clamped under the HDF5 chunk-size limit (ADVICE r4: the hi-res
        # preset's unclamped default chunk was 16 GiB and failed at open).
        self.chunks = _stream_chunks(
            chunks, nifs, nchans, self.dtype.itemsize, self._bitshuffle
        )
        self._h5 = h5py.File(self.path, "w")
        try:
            self._h5.attrs["CLASS"] = np.bytes_(b"FILTERBANK")
            self._h5.attrs["VERSION"] = np.bytes_(b"1.0")
            self._ds = self._h5.create_dataset(
                "data",
                shape=(0, nifs, nchans),
                maxshape=(None, nifs, nchans),
                dtype=self.dtype,
                chunks=self.chunks,
                **kw,
            )
            _header_attrs(self._ds, header)
        except BaseException:
            self._h5.close()
            os.unlink(self.path)
            raise
        self.nsamps = 0  # spectra durably in the dataset
        # Product manifest (ISSUE 13): logical-row digests folded as
        # slabs append; the whole-file CRC is computed by one re-read at
        # close (libhdf5 metadata churn makes mid-stream file-byte CRCs
        # meaningless — the fbh5 manifest digests the DATA rows).
        from blit import integrity

        self._mf = integrity.ManifestWriter(
            self.final_path, "fbh5",
            row_bytes=nifs * nchans * self.dtype.itemsize,
            writer=type(self).__name__)
        # Pending partial chunk row (the bitshuffle path buffers up to one;
        # the plain/gzip paths let libhdf5 chunk and never touch this).
        self._buf = (
            np.empty(self.chunks, self.dtype) if self._bitshuffle else None
        )
        self._buffered = 0

    def append(self, slab: np.ndarray) -> None:
        """Append ``(k, nifs, nchans)`` spectra to the time axis."""
        if slab.ndim != 3 or slab.shape[1:] != self._ds.shape[1:]:
            raise ValueError(
                f"append: slab shape {slab.shape} does not extend "
                f"(*, {self._ds.shape[1]}, {self._ds.shape[2]})"
            )
        if not self._bitshuffle:
            k = slab.shape[0]

            def _write():
                # Absolute resize + fixed-offset assignment: safe to retry.
                faults.fire("fbh5.write", key=self.path)
                self._ds.resize(self.nsamps + k, axis=0)
                self._ds[self.nsamps:] = slab
            faults.retry_io(_write, describe=f"fbh5 write {self.path}")
            self.nsamps += k
            # Digest the STORED dtype bytes (h5py casts on assignment).
            self._mf.fold(np.ascontiguousarray(slab, self.dtype))
            self._mf.claim(self.nsamps)
            return
        self._buffer_slab(slab)

    def flush(self) -> None:
        """Flush libhdf5 buffers to the OS — the write-behind sink's
        flush barrier hook (:meth:`blit.outplane.AsyncSink.flush`).
        Does NOT flush a buffered partial bitshuffle chunk row (that
        happens at :meth:`close`, padded, exactly once)."""
        if self._h5 is not None:
            self._h5.flush()

    def close(self) -> None:
        """Flush any partial tail chunk, finalize, and rename onto the
        final path.  A failure anywhere in here (tail flush, HDF5 close,
        rename) drops the ``.partial`` before re-raising — close must
        never leave a stray partial behind."""
        if self._h5 is None:
            return
        try:
            if self._bitshuffle and self._buffered:
                self._flush_chunk(self._buffered)
            self._h5.close()
            self._h5 = None
            os.replace(self.path, self.final_path)
        except BaseException:
            self.abort()
            raise
        # Whole-file digest over the finished bytes (one re-read,
        # page-cache hot); best-effort — a manifest failure must never
        # un-publish the product.
        self._mf.publish(scan_file=True)

    def abort(self) -> None:
        """Drop the partial product (crash/exception path)."""
        if self._h5 is not None:
            self._h5.close()
            self._h5 = None
        if os.path.exists(self.path):
            os.unlink(self.path)

    def __enter__(self):
        return self

    def __exit__(self, etype, _e, _tb):
        if etype is None:
            self.close()
        else:
            self.abort()


class ResumableFBH5Writer(_ChunkStream):
    """Crash-resumable FBH5 product writer — the ``.h5`` twin of
    :class:`blit.pipeline.ResumableFilWriter` (VERDICT r4: BL's products
    are FBH5, src/gbtworkerfunctions.jl:141-155, and a long-scan reduction
    to the native format must survive a crash).

    Incompleteness marker is the cursor sidecar, not a ``.partial`` rename:
    slabs land in the time-resizable dataset and are flushed + fsync'd
    BEFORE the cursor claims them, so a crash leaves a resumable prefix —
    never a cursor ahead of durable data.  ``start_rows`` > 0 resumes by
    ``resize``-truncating the dataset to that many spectra (dropping any
    un-checkpointed tail) and clamping the cursor to match.

    Durability granularity: the plain/gzip paths checkpoint after every
    append; the bitshuffle path buffers up to one chunk row (exactly as
    :class:`FBH5Writer`) and the cursor claims only rows flushed as full
    chunks — buffered rows are re-reduced after a crash, and every claim
    (hence every resume point) is chunk-aligned.  Callers that truncate to
    an externally agreed restart offset (the mesh writer's pod-wide MIN)
    must pick chunk rows dividing that offset's granularity; pass
    ``chunks=`` to arrange it.

    The cursor is duck-typed (``frames_done`` + ``save(path)`` — a
    :class:`blit.pipeline.ReductionCursor`); ``nint`` converts written
    rows to its frame count.
    """

    def __init__(self, path: str, header: Dict, nifs: int, nchans: int,
                 start_rows: int, nint: int, cursor,
                 compression: Optional[str] = None,
                 chunks: Optional[Tuple[int, int, int]] = None,
                 dtype=np.float32):
        self.path = path
        self.dtype = np.dtype(dtype)
        self._nifs, self._nchans = nifs, nchans
        self._nint = nint
        self.cursor = cursor
        kw, self._bitshuffle = _compression_kwargs(
            compression, self.dtype.itemsize
        )
        self.chunks = _stream_chunks(
            chunks, nifs, nchans, self.dtype.itemsize, self._bitshuffle
        )
        if self._bitshuffle and start_rows % self.chunks[0]:
            raise ValueError(
                f"bitshuffle resume point {start_rows} rows is not "
                f"aligned to chunk rows {self.chunks[0]} — the cursor "
                "only ever claims chunk-aligned counts, so this is a "
                "caller bug (restart offset granularity must be a "
                "multiple of chunk rows)"
            )
        if start_rows > 0 and os.path.exists(path):
            self._h5 = h5py.File(path, "r+")
            try:
                self._ds = self._h5["data"]
                if self._ds.shape[1:] != (nifs, nchans):
                    raise ValueError(
                        f"resume target {path} has dataset shape "
                        f"{self._ds.shape}, product needs (*, {nifs}, "
                        f"{nchans})"
                    )
                if self._ds.chunks != self.chunks:
                    raise ValueError(
                        f"resume target {path} has chunks {self._ds.chunks}"
                        f", writer needs {self.chunks} — cursor identity "
                        "should have refused this resume"
                    )
                # A dataset's filter pipeline is fixed at creation; direct
                # chunk writes through a MISMATCHED pipeline would store
                # undecodable payloads, so refuse rather than corrupt.
                has_bshuf = _bitshuffle_cd_values(self._ds) is not None
                if has_bshuf != self._bitshuffle:
                    raise ValueError(
                        f"resume target {path} "
                        f"{'has' if has_bshuf else 'lacks'} the bitshuffle "
                        "filter but the writer "
                        f"{'expects' if self._bitshuffle else 'does not use'}"
                        " it — cursor identity should have refused this"
                    )
                if self._ds.shape[0] < start_rows:
                    raise ValueError(
                        f"resume target {path} holds {self._ds.shape[0]} "
                        f"spectra, cursor claims {start_rows}"
                    )
                # Drop the un-checkpointed tail; clamp the cursor DOWN with
                # the truncation (mesh restarts at a pod-wide minimum).
                self._ds.resize(start_rows, axis=0)
                self._checkpoint(start_rows)
            except BaseException:
                self._h5.close()
                raise
        else:
            start_rows = 0
            self._h5 = h5py.File(path, "w")
            try:
                self._h5.attrs["CLASS"] = np.bytes_(b"FILTERBANK")
                self._h5.attrs["VERSION"] = np.bytes_(b"1.0")
                self._ds = self._h5.create_dataset(
                    "data",
                    shape=(0, nifs, nchans),
                    maxshape=(None, nifs, nchans),
                    dtype=self.dtype,
                    chunks=self.chunks,
                    **kw,
                )
                _header_attrs(self._ds, header)
                self._checkpoint(0)
            except BaseException:
                self._h5.close()
                os.unlink(path)
                raise
        self.nsamps = start_rows
        # Product manifest (ISSUE 13): the claim ledger checkpoints
        # beside the cursor, so a resume can content-verify the claimed
        # rows (resume_target_ok) before trusting it.  On resume the
        # running digest is rebuilt over the truncated claim (callers
        # already verified it matches the ledger).
        from blit import integrity

        self._mf = integrity.ManifestWriter(
            path, "fbh5", row_bytes=nifs * nchans * self.dtype.itemsize,
            writer=type(self).__name__)
        if start_rows > 0:
            row_bytes = nifs * nchans * self.dtype.itemsize
            step = max(1, (8 << 20) // max(1, row_bytes))
            manual = _needs_manual_bitshuffle(self._ds)
            for a in range(0, start_rows, step):
                b = min(start_rows, a + step)
                slab = (
                    _read_bitshuffle_chunks(
                        self._ds, ((a, b), (0, nifs), (0, nchans)))
                    if manual else self._ds[a:b]
                )
                self._mf.fold(np.ascontiguousarray(slab, self.dtype))
            self._mf.claim(start_rows)
        self._mf.save()
        self._buf = (
            np.empty(self.chunks, self.dtype) if self._bitshuffle else None
        )
        self._buffered = 0

    def _checkpoint(self, rows: int) -> None:
        """Durable data BEFORE the cursor claims it (power-loss
        ordering): flush libhdf5 buffers, fsync the file, persist the
        MANIFEST (its ledger must always hold an entry for every row
        count a cursor can claim — ahead is harmless, behind is an
        unverifiable gap), then the cursor."""
        self._h5.flush()
        os.fsync(self._h5.id.get_vfd_handle())
        mf = getattr(self, "_mf", None)
        if mf is not None:  # absent only during __init__'s own call
            mf.save()
        self.cursor.frames_done = rows * self._nint
        self.cursor.save(self.path)

    def append(self, slab: np.ndarray) -> None:
        """Append ``(k, nifs, nchans)`` spectra and checkpoint every row
        (plain/gzip) or every completed chunk (bitshuffle)."""
        if slab.ndim != 3 or slab.shape[1:] != (self._nifs, self._nchans):
            raise ValueError(
                f"append: slab shape {slab.shape} does not extend "
                f"(*, {self._nifs}, {self._nchans})"
            )
        if not self._bitshuffle:
            k = slab.shape[0]

            def _write():
                faults.fire("fbh5.write", key=self.path)
                self._ds.resize(self.nsamps + k, axis=0)
                self._ds[self.nsamps:] = slab
            faults.retry_io(_write, describe=f"fbh5 write {self.path}")
            self.nsamps += k
            self._mf.fold(np.ascontiguousarray(slab, self.dtype))
            self._mf.claim(self.nsamps)
            self._checkpoint(self.nsamps)  # saves manifest, then cursor
            return
        if self._buffer_slab(slab):
            # _flush_chunk already folded + claimed the flushed rows.
            self._checkpoint(self.nsamps)

    def close(self) -> None:
        """Flush any buffered tail (bitshuffle pads the final chunk, as
        the upstream filter does), finalize, and remove the sidecar — its
        absence is the completeness marker."""
        if self._h5 is None:
            return
        if self._bitshuffle and self._buffered:
            self._flush_chunk(self._buffered)
        self._h5.flush()
        os.fsync(self._h5.id.get_vfd_handle())
        self._h5.close()
        self._h5 = None
        # Completed product: whole-file digest (the manifest stays; the
        # cursor sidecar below goes — its absence marks completeness).
        self._mf.publish(scan_file=True)
        # The cursor names its own sidecar when it can (StreamCursor's
        # ``.stream-cursor`` sibling, blit/stream/cursor.py); the duck-
        # typed fallback keeps the ReductionCursor ``.cursor`` default.
        path_for = getattr(self.cursor, "path_for", _cursor_path)
        sidecar = path_for(self.path)
        if os.path.exists(sidecar):
            os.unlink(sidecar)

    def abort(self) -> None:
        """The file + cursor ARE the resume point: close, keep both.
        Buffered (unclaimed) bitshuffle rows are simply dropped — the
        cursor never claimed them, so the resume re-reduces them."""
        if self._h5 is not None:
            self._h5.close()
            self._h5 = None


def _cursor_path(out_path: str) -> str:
    """Sidecar path, kept in lockstep with
    ``blit.pipeline.ReductionCursor.path_for`` (imported lazily there to
    keep blit.io free of pipeline dependencies)."""
    return out_path + ".cursor"


def resume_target_ok(path: str, nifs: int, nchans: int, rows: int) -> bool:
    """Can ``path`` back a resume claiming ``rows`` spectra?

    The crash-resume protocol fsyncs data before the cursor claims it,
    but libhdf5's in-place metadata updates between checkpoints are NOT
    crash-atomic: a SIGKILL/power loss can leave a file that no longer
    opens as HDF5 — or whose claimed prefix no longer reads — while the
    cursor sidecar (written via its own tmp-rename+fsync) still parses
    (ADVICE r5 medium).  Resume callers probe with this BEFORE trusting
    the cursor: ``False`` means fall back to a fresh start exactly like
    a cursor-identity mismatch (logging what was discarded), instead of
    raising and wedging resume until an operator deletes the file by
    hand.

    The probe opens the file, checks the dataset geometry covers the
    claim, and decodes the last claimed row (one chunk read — under
    bitshuffle the cursor only ever claims flushed chunks, so that row
    must decode).  Any failure anywhere is a ``False``, not an error.

    When a manifest sidecar exists the structural probe is UPGRADED to
    content verification (ISSUE 13): the claimed rows' digest must match
    the manifest's claim ledger — bit rot or a torn write *inside* the
    claimed region fails closed where the decode probe alone would have
    resumed onto (structurally valid) corrupt spectra.  No manifest
    keeps the structural behavior.
    """
    try:
        with h5py.File(path, "r") as h5:
            ds = h5["data"]
            if ds.shape[1:] != (nifs, nchans) or ds.shape[0] < rows:
                return False
        if rows > 0:
            read_fbh5_data(
                path, (slice(rows - 1, rows), slice(None), slice(None))
            )
    except Exception:  # noqa: BLE001 — any unreadability means start fresh
        return False
    from blit import integrity

    return integrity.verify_claim(path, rows, fmt="fbh5") is not False


def write_fbh5(
    path: str,
    header: Dict,
    data: np.ndarray,
    compression: Optional[str] = None,
    chunks: Optional[Tuple[int, int, int]] = None,
) -> None:
    """Write an FBH5 file: ``data`` dataset + header attributes.

    ``compression``: None | "gzip" | "bitshuffle" (bitshuffle requires the
    native codec from ``blit/native``; raises if unbuilt).
    """
    if data.ndim != 3:
        raise ValueError("write_fbh5: data must be (nsamps, nifs, nchans)")
    bitshuffle = False
    kw = {}
    if chunks is not None:
        kw["chunks"] = chunks
    if compression == "gzip":
        kw["compression"] = "gzip"
        kw.setdefault("chunks", True)
    elif compression == "bitshuffle":
        from blit.io import bshuf

        if not bshuf.available():
            raise RuntimeError(
                "bitshuffle codec unavailable; build blit/native first"
            )
        bitshuffle = True
        dc = default_chunks(data.shape[1], data.shape[2], data.dtype.itemsize)
        kw["chunks"] = chunks or (max(1, min(data.shape[0], dc[0])), dc[1], dc[2])
        kw["compression"] = BITSHUFFLE_FILTER_ID
        kw["compression_opts"] = bshuf.filter_cd_values(data.dtype.itemsize)
        kw["allow_unknown_filter"] = True
    elif compression is not None:
        raise ValueError(f"unknown compression {compression!r}")

    with h5py.File(path, "w") as h5:
        h5.attrs["CLASS"] = np.bytes_(b"FILTERBANK")
        h5.attrs["VERSION"] = np.bytes_(b"1.0")
        if bitshuffle:
            ds = h5.create_dataset(
                "data", shape=data.shape, dtype=data.dtype, **kw
            )
            _write_bitshuffle_chunks(ds, np.ascontiguousarray(data))
        else:
            ds = h5.create_dataset("data", data=data, **kw)
        _header_attrs(ds, header)
