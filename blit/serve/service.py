"""``ProductService`` — the multi-tenant front door (ISSUE 3 tentpole).

Turns blit from a one-caller library into a product service: callers
``submit()`` product requests and get tickets; identical requests share
work at every level —

- **completed** requests hit the two-tier content-addressed
  :class:`~blit.serve.cache.ProductCache` (RAM, then disk) and return
  without touching the GUPPI layer at all;
- **in-flight** requests COALESCE: a single-flight group per reduction
  fingerprint means N concurrent callers asking for the same product run
  ONE reduction, and every caller gets the same (byte-identical,
  read-only) result array;
- **new** requests are admitted through the
  :class:`~blit.serve.scheduler.Scheduler` (bounded queues, fair share,
  health-aware concurrency budget) onto the existing reduction machinery
  (:func:`blit.pipeline.reducer_for_product` /
  :class:`~blit.pipeline.RawReducer`).

Failures propagate the PR-2 error taxonomy per ticket
(``RemoteError(etype="HostDegraded")``, ``TimeoutError``,
``InjectedFault``, ...) and a failed flight is REMOVED from the
single-flight table — later identical requests start a fresh reduction
instead of being poisoned by a stale error.  Cancelling the last ticket
of a still-queued flight releases its scheduler slot.
"""

from __future__ import annotations

import logging
import threading
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple, Union

import numpy as np

from blit import observability
from blit.config import DEFAULT, SiteConfig
from blit.observability import Timeline
from blit.serve.cache import ProductCache, fingerprint_for
from blit.serve.scheduler import Cancelled, Job, Overloaded, Scheduler

log = logging.getLogger("blit.serve.service")


@dataclass(frozen=True)
class ProductRequest:
    """One product ask: which raw recording, reduced how.

    ``product`` selects a rawspec preset ("0000"/"0001"/"0002",
    :data:`blit.pipeline.PRODUCT_PRESETS`); otherwise ``nfft``/``nint``
    configure the reduction directly (exactly the
    :func:`blit.workers.reduce_raw` contract).  ``raw`` may be a single
    path or a multi-file sequence member list — member ORDER does not
    change the request's identity (fingerprints normalize it).

    ``kind="hits"`` asks for a drift-rate search product instead of a
    filterbank (ISSUE 6): the reduction runs a
    :class:`blit.search.dedoppler.DedopplerReducer` and the result array
    is the dense hit-table encoding
    (:func:`blit.search.hits.hits_from_array` decodes it under the
    returned header).  The search knobs join the fingerprint, so cached
    ``.hits`` and ``.fil`` products of the same recording never collide,
    and identical concurrent searches single-flight like any other
    request.

    ``kind="catalog"`` asks for the archive catalog document instead of
    a product (ISSUE 19): ``raw`` carries the query string (``""``
    lists sessions, ``"<session>"`` one session's scans,
    ``"<session>/<scan>"`` one scan's membership) and the answer rides
    the header of an empty result array — served from the process's
    :class:`~blit.serve.catalog.CatalogIndex`, never cached or reduced.

    ``session``/``scan`` address a product LOGICALLY (ISSUE 19): leave
    ``raw`` empty and the front door (or a catalog-configured service)
    resolves the pair into the explicit member-path recipe via the
    catalog BEFORE fingerprinting — so the logical ask and the
    equivalent explicit-path ask are the same request (same ring
    owner, same single-flight group, byte-identical product).

    ``kind="stream"`` admits a LIVE job (ISSUE 12 satellite, ROADMAP
    item 5): ``raw`` names a recording still being written, ``out`` the
    product path, and the job runs :func:`blit.stream.stream_reduce`
    (rejoinable, ``resume=True``) for the SESSION's duration.
    The scheduler admits the job under a capacity HOLD — it pins a
    concurrency slot but is excluded from the EWMA/deadline model,
    which assumes bounded jobs; ``session_s`` declares the expected
    session length, reported through ``stats()["held_declared_s"]`` so
    operators see how long the pin expects to last.  Live sessions are
    never cached or coalesced, and a second ask for an in-flight
    ``out`` is rejected (the bytes are still growing; the product
    lands on disk at ``out``) — the result tuple is ``(header, empty
    array)``."""

    raw: Union[str, Tuple[str, ...]]
    product: Optional[str] = None
    nfft: int = 1024
    nint: int = 1
    stokes: str = "I"
    fqav_by: int = 1
    dtype: str = "float32"
    # Product kind: "filterbank" (default) | "hits" (drift search) |
    # "stream" (live session, capacity-held).
    kind: str = "filterbank"
    # Search knobs (kind="hits" only; None -> SiteConfig/env defaults).
    window_spectra: Optional[int] = None
    snr_threshold: Optional[float] = None
    top_k: Optional[int] = None
    max_drift_bins: Optional[int] = None
    # Live-job knobs (kind="stream" only): product path, declared
    # session length (capacity-hold accounting), and the tail/replay
    # shaping passed through to stream_reduce's source.
    out: Optional[str] = None
    session_s: Optional[float] = None
    replay_rate: Optional[float] = None
    idle_timeout_s: Optional[float] = None
    # Logical archive addressing (ISSUE 19): resolved into member paths
    # through the catalog before fingerprinting; ``raw`` stays empty.
    session: Optional[str] = None
    scan: Optional[str] = None
    band: Optional[int] = None
    bank: Optional[int] = None

    def __post_init__(self):
        if isinstance(self.raw, list):
            object.__setattr__(self, "raw", tuple(self.raw))
        if self.product is not None and (self.nfft != 1024 or self.nint != 1):
            raise ValueError(
                "pass either product= or explicit nfft/nint, not both"
            )
        if self.kind not in ("filterbank", "hits", "stream", "catalog"):
            raise ValueError(f"unknown product kind {self.kind!r}")
        if self.kind == "catalog":
            if not isinstance(self.raw, str):
                raise ValueError("a catalog ask carries its query string "
                                 "in raw= (\"\", \"<session>\" or "
                                 "\"<session>/<scan>\")")
            if self.session is not None or self.scan is not None:
                raise ValueError("kind='catalog' queries via raw=; "
                                 "session=/scan= address PRODUCTS")
        if (self.session is None) != (self.scan is None):
            raise ValueError("logical addressing needs BOTH session= "
                             "and scan=")
        if self.session is not None:
            if self.kind not in ("filterbank", "hits"):
                raise ValueError("session=/scan= addressing applies to "
                                 "derivable products (filterbank/hits)")
            if self.raw not in ("", ()):
                raise ValueError("pass either raw= member paths or "
                                 "session=/scan=, not both")
        elif self.band is not None or self.bank is not None:
            raise ValueError("band=/bank= qualify session=/scan= "
                             "addressing")
        if self.kind != "hits" and any(
            v is not None for v in (self.window_spectra, self.snr_threshold,
                                    self.top_k, self.max_drift_bins)
        ):
            raise ValueError("search knobs require kind='hits'")
        if self.kind == "hits" and (self.stokes != "I" or self.fqav_by != 1):
            raise ValueError(
                "hits products search the Stokes-I stream un-averaged "
                "(stokes='I', fqav_by=1)"
            )
        if self.kind == "stream":
            if self.out is None:
                raise ValueError("kind='stream' needs out= (the live "
                                 "product's path)")
            if isinstance(self.raw, tuple):
                raise ValueError("a live session tails ONE growing "
                                 "recording (a .NNNN.raw member path)")
        elif any(v is not None for v in (self.out, self.session_s,
                                         self.replay_rate,
                                         self.idle_timeout_s)):
            raise ValueError("out/session_s/replay_rate/idle_timeout_s "
                             "require kind='stream'")

    def reducer(self):
        """The configured reducer for this ask: a
        :class:`blit.pipeline.RawReducer` for filterbanks, a
        :class:`blit.search.dedoppler.DedopplerReducer` for hits — both
        expose ``reduce(raw) -> (header, array)`` and the fingerprint
        knob surface, so the service treats them alike."""
        if self.kind == "catalog":
            raise ValueError("catalog asks are answered from the "
                             "CatalogIndex, not reduced")
        if self.kind == "stream":
            # The live job's reducer is a plain RawReducer (the stream
            # plane feeds the unchanged batch reducers); constructed
            # here so the service treats its knobs like any other's.
            from blit.pipeline import RawReducer, reducer_for_product

            kw = dict(stokes=self.stokes, fqav_by=self.fqav_by,
                      dtype=self.dtype)
            if self.product is not None:
                return reducer_for_product(self.product, **kw)
            return RawReducer(nfft=self.nfft, nint=self.nint, **kw)
        if self.kind == "hits":
            from blit.pipeline import PRODUCT_PRESETS
            from blit.search import DedopplerReducer

            nfft, nint = (
                PRODUCT_PRESETS[self.product] if self.product is not None
                else (self.nfft, self.nint)
            )
            return DedopplerReducer(
                nfft=nfft, nint=nint, dtype=self.dtype,
                window_spectra=self.window_spectra,
                snr_threshold=self.snr_threshold, top_k=self.top_k,
                max_drift_bins=self.max_drift_bins,
            )
        from blit.pipeline import RawReducer, reducer_for_product

        kw = dict(stokes=self.stokes, fqav_by=self.fqav_by, dtype=self.dtype)
        if self.product is not None:
            return reducer_for_product(self.product, **kw)
        return RawReducer(nfft=self.nfft, nint=self.nint, **kw)

    @property
    def raw_source(self):
        return list(self.raw) if isinstance(self.raw, tuple) else self.raw

    # Recipe fields a cache meta records (ISSUE 13): enough to rebuild
    # the request — and hence re-derive the entry — after a quarantine.
    _RECIPE_FIELDS = ("product", "nfft", "nint", "stokes", "fqav_by",
                     "dtype", "kind", "window_spectra", "snr_threshold",
                     "top_k", "max_drift_bins", "session", "scan",
                     "band", "bank")

    def recipe(self) -> Dict:
        """The JSON-able re-derivation recipe of this ask — stored in the
        disk cache's meta sidecar next to the fingerprint, so ``blit
        fsck --repair`` can rebuild a quarantined entry through the same
        reduce path the serve layer takes on a miss (the fingerprint is
        already a content-addressed recipe KEY; this makes it
        executable).  Live sessions are never cached, so never carry
        recipes."""
        d: Dict = {"raw": self.raw_source}
        for k in self._RECIPE_FIELDS:
            v = getattr(self, k)
            if v is not None:
                d[k] = v
        if d.get("product") is not None:
            # product= and explicit nfft/nint are mutually exclusive at
            # construction; the preset carries the pair.
            d.pop("nfft", None)
            d.pop("nint", None)
        return d

    @classmethod
    def from_recipe(cls, recipe: Dict) -> "ProductRequest":
        """Rebuild a request from a cache meta's recipe (unknown keys
        ignored so older blits can read newer recipes)."""
        kw = {k: recipe[k] for k in cls._RECIPE_FIELDS if k in recipe}
        return cls(raw=recipe["raw"], **kw)


class _Flight:
    """One single-flight group: every ticket for the same fingerprint
    submitted while the reduction is in flight rides this object."""

    __slots__ = ("fingerprint", "tickets", "job", "result", "exc", "done",
                 "source")

    def __init__(self, fingerprint: str):
        self.fingerprint = fingerprint
        self.tickets: List["Ticket"] = []
        self.job: Optional[Job] = None
        self.result: Optional[Tuple[Dict, np.ndarray]] = None
        self.exc: Optional[BaseException] = None
        self.done = threading.Event()
        # Live sessions only (kind="stream"): the ChunkSource feeding the
        # in-flight stream_reduce, kept so drain() can stop it gracefully
        # (ISSUE 14 satellite) — the session finishes with what arrived
        # and its capacity hold releases instead of leaking.
        self.source = None


@dataclass
class Ticket:
    """A claim on one submitted request.  ``source`` records how it was
    (or will be) satisfied: ``"ram"``/``"disk"``/``"cold"`` cache hits
    and ``"catalog"`` answers complete at submit time; ``"scheduled"``
    started the reduction; ``"coalesced"`` joined one already in
    flight — both rewrite to ``"derive"`` once the reduction lands, so
    access records report the serving TIER (ISSUE 19)."""

    fingerprint: str
    client: str
    source: str
    submitted_at: float = field(default_factory=time.monotonic)
    _flight: Optional[_Flight] = None
    _result: Optional[Tuple[Dict, np.ndarray]] = None
    cancelled: bool = False

    @property
    def done(self) -> bool:
        return (self._result is not None
                or self._flight is None
                or self._flight.done.is_set())

    def queue_wait_s(self) -> float:
        """Seconds this ticket's reduction sat queued (0.0 for cache
        hits and not-yet-dispatched flights) — the access record's
        queue-wait field (ISSUE 15)."""
        f = self._flight
        if f is None or f.job is None:
            return 0.0
        return f.job.wait_s or 0.0


class ProductService:
    """The serving front door (module docstring).  One instance per
    process; all methods are thread-safe.

    ``pool`` (optional) is the :class:`~blit.parallel.pool.WorkerPool`
    whose health shrinks the scheduler's concurrency budget; the
    reductions themselves run in the scheduler's job threads (the heavy
    lifting releases the GIL in NumPy/HDF5/XLA)."""

    def __init__(
        self,
        *,
        cache: Optional[ProductCache] = None,
        scheduler: Optional[Scheduler] = None,
        config: SiteConfig = DEFAULT,
        pool=None,
        timeline: Optional[Timeline] = None,
        catalog=None,
    ):
        from blit.config import archive_defaults, catalog_defaults

        self.timeline = timeline if timeline is not None else Timeline()
        self.cache = cache if cache is not None else ProductCache(
            config.cache_dir, ram_bytes=config.cache_ram_bytes,
            timeline=self.timeline,
            cold_dir=archive_defaults(config)["cold_dir"],
        )
        # Archive catalog (ISSUE 19): serves kind="catalog" asks and
        # resolves session=/scan= logical addressing.  Built when
        # BLIT_CATALOG_ROOT / SiteConfig.catalog_root names a tree (or
        # passed in ready-made); None otherwise — catalog asks then
        # fail loudly as caller errors.
        self.catalog = catalog
        if self.catalog is None and catalog_defaults(config)["enabled"]:
            from blit.serve.catalog import CatalogIndex

            self.catalog = CatalogIndex(config=config,
                                        timeline=self.timeline)
        self.scheduler = scheduler if scheduler is not None else Scheduler(
            max_concurrency=config.serve_max_concurrency,
            queue_depth=config.serve_queue_depth,
            pool=pool, timeline=self.timeline,
        )
        self._lock = threading.Lock()
        self._flights: Dict[str, _Flight] = {}
        # Graceful-drain latch (ISSUE 14): once set, submissions are
        # REFUSED with Overloaded (the HTTP layer answers 503 so a fleet
        # front door fails over to a replica) while in-flight work
        # finishes and live-session holds release.
        self._draining = False
        # In-flight live sessions' DECLARED lengths (kind="stream"
        # session_s; None = undeclared) — the operator-facing view of
        # how long the held capacity expects to stay pinned (stats()).
        self._live_declared: Dict[str, Optional[float]] = {}
        self.counts: Dict[str, int] = {
            "requests": 0, "coalesced": 0, "cache_hits": 0,
            "scheduled": 0, "rejected": 0,
        }
        # Per-request access records (ISSUE 15): library/bench callers
        # going through get() write one bounded JSON line per request —
        # None (one attribute test per request) unless BLIT_REQUEST_LOG
        # / SiteConfig.request_log_dir enables it.  The fleet peer's
        # HTTP handler keeps its OWN log (it submits tickets directly),
        # so one request never double-records.
        self.request_log = observability.request_log_for("serve", config)
        # Live monitoring (ISSUE 11): when the process-wide publisher is
        # enabled (BLIT_MONITOR_* / SiteConfig monitor_* knobs), this
        # service's timeline joins its watch set — queue depth, wait
        # tails and cache counters stream to the spool/endpoint while
        # requests flow — and SLO breaches shed THIS scheduler's
        # admission (Scheduler.shed) until the burn clears.
        from blit import monitor

        self._publisher = monitor.ensure_publisher(config)
        if self._publisher is not None:
            self._publisher.watch(self.timeline)
            self._publisher.slo.attach_scheduler(self.scheduler)
        # Background integrity scrubbing (ISSUE 13): opt-in via
        # BLIT_SCRUB_INTERVAL / SiteConfig.scrub_interval_s — samples
        # disk-tier entries between requests under a bytes/s budget,
        # quarantining what fails and publishing integrity.scrub.*
        # through the monitor plane.
        from blit.config import scrub_defaults

        self._scrubber = None
        sd = scrub_defaults(config)
        if sd["enabled"] and self.cache.root is not None:
            from blit.integrity import Scrubber

            self._scrubber = Scrubber(
                self.cache, interval_s=sd["interval_s"],
                bytes_per_s=sd["bytes_per_s"],
                timeline=self.timeline).start()

    # -- submission --------------------------------------------------------
    def submit(
        self,
        request: ProductRequest,
        *,
        priority: int = 1,
        client: str = "anon",
        deadline_s: Optional[float] = None,
    ) -> Ticket:
        """Admit one request.  Returns a :class:`Ticket` (possibly already
        complete — cache hits never enter the queue); raises
        :class:`~blit.serve.scheduler.Overloaded` when admission control
        refuses, and ``OSError`` when the raw input does not exist (an
        address over unknown bytes is a caller bug, found at the door)."""
        if self._draining:
            with self._lock:
                self.counts["rejected"] += 1
            raise Overloaded("service is draining (shutdown in "
                             "progress); retry another replica",
                             retry_after_s=self.scheduler._retry_after_s(
                                 1.0))
        if request.kind == "stream":
            if deadline_s is not None:
                # The deadline estimator models BOUNDED jobs; silently
                # queueing a session past a caller's deadline would be
                # the un-honored contract, so refuse loudly instead.
                raise ValueError(
                    "deadline_s does not apply to kind='stream' live "
                    "sessions (they run for the recording's duration)")
            return self._submit_stream(request, priority, client)
        if request.kind == "catalog":
            return self._submit_catalog(request, client)
        if request.session is not None:
            request = self.resolve_request(request)
        reducer = request.reducer()
        fp = fingerprint_for(reducer, request.raw_source)
        with self._lock:
            self.counts["requests"] += 1
        # Completed products serve straight from the cache — the hot path
        # never touches the GUPPI layer (acceptance: the guppi.read
        # injection point stays cold on hits).
        hit = self.cache.get(fp)
        if hit is not None:
            header, data, tier = hit
            with self._lock:
                self.counts["cache_hits"] += 1
            return Ticket(fp, client, tier, _result=(header, data))
        with self._lock:
            flight = self._flights.get(fp)
            if flight is not None:
                # Single-flight coalescing: ride the running reduction.
                t = Ticket(fp, client, "coalesced", _flight=flight)
                flight.tickets.append(t)
                self.counts["coalesced"] += 1
                self.timeline.count("serve.coalesced")
                return t
            flight = _Flight(fp)
            t = Ticket(fp, client, "scheduled", _flight=flight)
            flight.tickets.append(t)
            self._flights[fp] = flight
            # Capture the submitter's trace context NOW: the reduction
            # runs later on a scheduler job thread, and its span must
            # parent onto the request that scheduled it (ISSUE 5) — N
            # coalesced callers all point at this one flight span tree.
            ctx = observability.tracer().context()
            try:
                flight.job = self.scheduler.submit(
                    lambda: self._reduce_and_publish(fp, request, flight,
                                                     ctx),
                    priority=priority, client=client, deadline_s=deadline_s,
                    # Dispatch-time deadline expiry DROPS the job
                    # without running fn — the flight must still fail,
                    # or waiters hang and later identical requests
                    # coalesce onto a dead group forever.
                    on_drop=lambda e: self._finish(fp, flight, exc=e),
                )
            except BaseException as e:
                # ANY admission failure (Overloaded, a closed scheduler,
                # ...) must drop the flight from the table — a leaked
                # jobless flight would make every later identical request
                # coalesce onto it and hang forever.
                del self._flights[fp]
                if isinstance(e, Overloaded):
                    self.counts["rejected"] += 1
                raise
            self.counts["scheduled"] += 1
        return t

    def wire_for(self, request: ProductRequest
                 ) -> Optional[Tuple[str, bytes, str]]:
        """The already-encoded binary wire body for ``request`` when
        the cache retains one (ISSUE 16): ``(fingerprint, frame bytes,
        tier)``, or ``None`` — a miss here is NOT a cache miss; the
        caller falls back to :meth:`submit`, which counts and serves.
        A draining service answers ``None`` too, so the refusal runs
        through submit's :class:`Overloaded` → 503 contract unchanged.
        """
        if self._draining or request.kind in ("stream", "catalog"):
            return None
        if request.session is not None:
            try:
                request = self.resolve_request(request)
            except Exception:
                # submit() is the authoritative error surface; a wire
                # miss just falls back to it.
                return None
        fp = fingerprint_for(request.reducer(), request.raw_source)
        hit = self.cache.get_wire(fp)
        if hit is None:
            return None
        body, tier = hit
        with self._lock:
            self.counts["requests"] += 1
            self.counts["cache_hits"] += 1
        return fp, body, tier

    def resolve_request(self, request: ProductRequest) -> ProductRequest:
        """Substitute ``session=``/``scan=`` logical addressing with the
        catalog's member-path list (ISSUE 19).  Identity-preserving by
        construction: the result IS the equivalent explicit-member-path
        request — same fingerprint, same ring owner, same single-flight
        group, byte-identical product."""
        if request.session is None:
            return request
        if self.catalog is None:
            raise ValueError(
                "session=/scan= addressing needs a catalog "
                "(BLIT_CATALOG_ROOT / SiteConfig.catalog_root)")
        import dataclasses

        members = self.catalog.resolve(
            request.session, request.scan,
            band=request.band, bank=request.bank)
        return dataclasses.replace(
            request, raw=tuple(members),
            session=None, scan=None, band=None, bank=None)

    def _submit_catalog(self, request: ProductRequest,
                        client: str) -> Ticket:
        """Answer a ``kind="catalog"`` ask from the process's
        :class:`~blit.serve.catalog.CatalogIndex` — synchronous (an
        in-RAM index read; a ticket keeps the caller surface uniform),
        never cached, never coalesced, never queued."""
        from blit.serve.catalog import catalog_fingerprint

        with self._lock:
            self.counts["requests"] += 1
        if self.catalog is None:
            raise ValueError(
                "no catalog configured (BLIT_CATALOG_ROOT / "
                "SiteConfig.catalog_root)")
        header, data = self.catalog.serve(request.raw)
        fp = catalog_fingerprint((request.raw or "").strip("/"))
        self.timeline.count("serve.catalog")
        return Ticket(fp, client, "catalog", _result=(header, data))

    def _submit_stream(self, request: ProductRequest, priority: int,
                       client: str) -> Ticket:
        """Admit a LIVE job (ISSUE 12 satellite): no cache hit is
        possible over still-growing bytes and no coalescing is safe —
        two live consumers of one session would interleave appends on
        ONE product path and its rejoin sidecar — so a second ask for
        an in-flight ``out`` is REJECTED with :class:`Overloaded`
        (retry once the session ends; a crashed session's restart goes
        through `blit.recover.StreamSupervisor`, not a duplicate
        submit).  Admitted sessions go straight to the scheduler under
        a session-length capacity hold."""
        fp = f"live:{request.out}"
        with self._lock:
            self.counts["requests"] += 1
            if fp in self._flights:
                self.counts["rejected"] += 1
                raise Overloaded(
                    f"live session already in flight for {request.out}; "
                    "retry after it ends")
            flight = _Flight(fp)
            t = Ticket(fp, client, "scheduled", _flight=flight)
            flight.tickets.append(t)
            self._flights[fp] = flight
            ctx = observability.tracer().context()
            try:
                flight.job = self.scheduler.submit(
                    lambda: self._run_stream(request, flight, ctx),
                    priority=priority, client=client, hold=True,
                )
            except BaseException as e:
                del self._flights[fp]  # the bounded-path leak rule
                if isinstance(e, Overloaded):
                    self.counts["rejected"] += 1
                raise
            self._live_declared[fp] = request.session_s
            self.counts["scheduled"] += 1
            self.timeline.count("serve.live_sessions")
        return t

    def _run_stream(self, request: ProductRequest, flight: _Flight,
                    ctx=None) -> Tuple[Dict, np.ndarray]:
        tr = observability.tracer()
        try:
            with tr.activate(ctx), \
                    tr.span("serve.stream", out=request.out), \
                    self.timeline.stage("serve.stream", byte_free=True):
                from blit.stream import (
                    FileTailSource,
                    ReplaySource,
                    stream_reduce,
                )

                reducer = request.reducer()
                if request.replay_rate:
                    src = ReplaySource(request.raw,
                                       rate=request.replay_rate)
                else:
                    src = FileTailSource(
                        request.raw,
                        idle_timeout_s=request.idle_timeout_s)
                flight.source = src  # drain() stops it gracefully
                hdr = stream_reduce(src, request.out, reducer=reducer,
                                    resume=True)
            data = np.zeros(
                (0, int(hdr.get("nifs", 1)), int(hdr.get("nchans", 0))),
                np.float32)
            data.setflags(write=False)
            self._finish(flight.fingerprint, flight, result=(hdr, data))
            return hdr, data
        except BaseException as e:  # noqa: BLE001 — per-ticket delivery
            self._finish(flight.fingerprint, flight, exc=e)
            raise

    def _reduce_and_publish(
        self, fp: str, request: ProductRequest, flight: _Flight, ctx=None
    ) -> Tuple[Dict, np.ndarray]:
        """The scheduled job body: run the reduction, publish to the
        cache, fulfill (or fail) every ticket on the flight.  ``ctx`` is
        the submitter's trace context — the job thread adopts it so the
        reduction's spans parent onto the request."""
        tr = observability.tracer()
        try:
            with tr.activate(ctx), \
                    tr.span("serve.reduce", fp=fp[:16]) as sp, \
                    self.timeline.stage("serve.reduce", byte_free=True):
                # Construct INSIDE the span/stage: reducer construction
                # (tuning-profile lookup, and at hi-res nfft the PFB
                # coefficient build) is request work — it must show in
                # the request's timing and parent onto its trace.  The
                # resolved profile key lands on the live span so a trace
                # names which knob set served the request.
                reducer = request.reducer()
                prov_fn = getattr(reducer, "tuning_provenance", None)
                prov = prov_fn() if prov_fn is not None else {}
                tuned = prov.get("profile", {}).get("key", "")[:16]
                if sp is not None and tuned:
                    sp.attrs = dict(sp.attrs or {}, tuned=tuned)
                header, data = reducer.reduce(request.raw_source)
            data = self.cache.put(fp, header, data,
                                  recipe=request.recipe())
            # Tier accounting (ISSUE 19): this request was satisfied by
            # DERIVATION — every ticket on the flight (scheduler and
            # coalescers alike) reports tier "derive", completing the
            # {ram, wire, disk, cold, derive} per-request tier story.
            self.cache.note_derive()
            with self._lock:
                for t in flight.tickets:
                    t.source = "derive"
            self._finish(fp, flight, result=(header, data))
            return header, data
        except BaseException as e:  # noqa: BLE001 — per-ticket delivery
            # Fail THIS flight's tickets but drop the group from the
            # table: a later identical request must start fresh, not be
            # poisoned by a stale error (transient faults recover).
            self._finish(fp, flight, exc=e)
            raise

    def _finish(
        self,
        fp: str,
        flight: _Flight,
        result: Optional[Tuple[Dict, np.ndarray]] = None,
        exc: Optional[BaseException] = None,
    ) -> None:
        with self._lock:
            if self._flights.get(fp) is flight:
                del self._flights[fp]
            self._live_declared.pop(fp, None)
            flight.result = result
            flight.exc = exc
        flight.done.set()

    # -- results -----------------------------------------------------------
    def result(
        self, ticket: Ticket, timeout: Optional[float] = None
    ) -> Tuple[Dict, np.ndarray]:
        """Block until the ticket's product is ready → ``(header, data)``
        with ``data`` read-only ``(nsamps, nif, nchans)`` float32.  Raises
        the flight's failure for this ticket (PR-2 taxonomy passes
        through), :class:`Cancelled` for a cancelled ticket, and the
        builtin ``TimeoutError`` past ``timeout``."""
        if ticket.cancelled:
            raise Cancelled("ticket was cancelled")
        if ticket._result is not None:
            return ticket._result
        flight = ticket._flight
        if flight is None or not flight.done.wait(timeout):
            raise TimeoutError(
                f"product {ticket.fingerprint[:16]}… not ready within "
                f"{timeout}s"
            )
        if ticket.cancelled:
            raise Cancelled("ticket was cancelled")
        if flight.exc is not None:
            raise flight.exc
        ticket._result = flight.result
        return flight.result

    def get(
        self,
        request: ProductRequest,
        *,
        timeout: Optional[float] = None,
        priority: int = 1,
        client: str = "anon",
        deadline_s: Optional[float] = None,
    ) -> Tuple[Dict, np.ndarray]:
        """Synchronous convenience: ``submit`` + ``result``.  When
        request logging is enabled (ISSUE 15), every call — served,
        refused or failed — appends exactly one access record."""
        if self.request_log is None:
            return self.result(
                self.submit(request, priority=priority, client=client,
                            deadline_s=deadline_s),
                timeout=timeout,
            )
        t0 = time.perf_counter()
        ctx = observability.tracer().context()
        status, code, ticket, nbytes = "error", 500, None, 0
        try:
            ticket = self.submit(request, priority=priority,
                                 client=client, deadline_s=deadline_s)
            header, data = self.result(ticket, timeout=timeout)
            nbytes = data.nbytes
            status, code = "ok", 200
            return header, data
        except BaseException as e:
            from blit.serve.scheduler import classify_failure

            status, code = classify_failure(e)
            raise
        finally:
            dt = time.perf_counter() - t0
            self.request_log.record(
                rid=observability.new_id(),
                trace=(ctx or {}).get("trace"), role="serve",
                client=client, priority=priority,
                fp=(ticket.fingerprint[:16] if ticket else None),
                tier=(ticket.source if ticket else None),
                queue_wait_s=(round(ticket.queue_wait_s(), 6)
                              if ticket else None),
                deadline_s=deadline_s,
                deadline_left_s=(round(deadline_s - dt, 6)
                                 if deadline_s is not None else None),
                status=status, code=code, bytes=nbytes,
                duration_s=round(dt, 6))

    def cancel(self, ticket: Ticket) -> bool:
        """Withdraw a ticket.  The LAST ticket of a still-queued flight
        cancels the underlying job and releases its queue slot; a flight
        whose reduction is already running completes anyway (its product
        is cached for the next asker).  Returns True when the ticket was
        withdrawn before completion."""
        with self._lock:
            if ticket.cancelled or ticket._result is not None:
                return False
            flight = ticket._flight
            if flight is None or flight.done.is_set():
                return False
            ticket.cancelled = True
            if ticket in flight.tickets:
                flight.tickets.remove(ticket)
            if flight.tickets or flight.job is None:
                return True
            job = flight.job
        if self.scheduler.cancel(job):
            self._finish(ticket.fingerprint, flight,
                         exc=Cancelled("all tickets cancelled"))
        return True

    # -- reporting / teardown ---------------------------------------------
    def stats(self) -> Dict[str, object]:
        """Serving counters + cache counters + queue-wait percentiles —
        the ``serve-bench`` CLI's report body."""
        with self._lock:
            out: Dict[str, object] = dict(self.counts)
            out["inflight"] = len(self._flights)
        cache = self.cache.stats()
        out["cache"] = cache
        served = (cache["hit.ram"] + cache["hit.disk"]
                  + cache.get("hit.cold", 0))
        total = served + cache["miss"]
        out["hit_rate"] = round(served / total, 4) if total else 0.0
        if self.catalog is not None:
            out["catalog"] = self.catalog.stats()
        out["queue_wait"] = self.scheduler.wait_percentiles()
        out["budget"] = self.scheduler.effective_budget()
        out["shed"] = self.scheduler.shed_level()
        # Capacity pinned by live sessions (ISSUE 12 satellite): the
        # held slots are budget the bounded-job estimator cannot use;
        # held_declared_s totals the in-flight sessions' DECLARED
        # lengths (session_s) so an operator sees how long that pin
        # expects to last.
        out["held"] = self.scheduler.held()
        with self._lock:
            out["held_declared_s"] = sum(
                s for s in self._live_declared.values() if s)
        return out

    def drain(self, timeout: Optional[float] = 30.0) -> Dict[str, int]:
        """Graceful shutdown (ISSUE 14 satellite — the SIGTERM path):

        1. refuse new submissions (:class:`Overloaded`; the HTTP layer
           answers 503 so a fleet front door fails over to a replica),
        2. STOP every in-flight live session's chunk source — the
           session finishes cleanly with the chunks that arrived, its
           resumable cursor stays rejoinable, and its ``kind="stream"``
           capacity hold RELEASES instead of leaking on interpreter
           exit,
        3. cancel still-queued jobs and wait for running ones
           (:meth:`Scheduler.drain`).

        Returns ``{"cancelled": queued jobs cancelled, "stopped": live
        sources stopped}``.  Idempotent; ``close()`` afterwards is
        still the teardown."""
        self._draining = True
        # Live flights whose job was just dispatched may not have built
        # their source yet (the submit→_run_stream window) — poll
        # briefly so a drain racing a fresh session still stops it.
        deadline = time.monotonic() + 2.0
        while True:
            with self._lock:
                live = [f for fp, f in self._flights.items()
                        if fp.startswith("live:") and not f.done.is_set()]
                sources = [f.source for f in live if f.source is not None]
            if len(sources) == len(live) or time.monotonic() >= deadline:
                break
            time.sleep(0.01)
        stopped = 0
        for src in sources:
            try:
                src.stop()
                stopped += 1
            except Exception:  # noqa: BLE001 — drain must not die mid-way
                log.warning("drain: stopping a live source failed",
                            exc_info=True)
        self.timeline.count("serve.drain")
        cancelled = self.scheduler.drain(timeout)
        # Flights whose job was cancelled while queued never reached
        # _reduce_and_publish — deliver Cancelled to their tickets so no
        # waiter blocks on a drained service forever.
        with self._lock:
            orphaned = [(fp, f) for fp, f in list(self._flights.items())
                        if f.job is not None and f.job.state == "cancelled"]
        for fp, flight in orphaned:
            self._finish(fp, flight,
                         exc=Cancelled("service drained while queued"))
        return {"cancelled": cancelled, "stopped": stopped}

    def draining(self) -> bool:
        return self._draining

    def close(self, timeout: Optional[float] = 30.0) -> None:
        if self.request_log is not None:
            self.request_log.close()
        if self._scrubber is not None:
            self._scrubber.close()
            self._scrubber = None
        if self._publisher is not None:
            if self._publisher.history is not None:
                # One last sample BEFORE this timeline leaves the watch
                # set (ISSUE 20): the tail of the service's activity —
                # everything since the previous interval tick — lands
                # in the durable history rings instead of vanishing.
                try:
                    self._publisher.tick()
                except Exception:  # noqa: BLE001 — teardown must finish
                    log.warning("final history tick failed",
                                exc_info=True)
            self._publisher.unwatch(self.timeline)
            self._publisher.slo.detach_scheduler(self.scheduler)
            self._publisher = None
        self.scheduler.close(timeout)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
