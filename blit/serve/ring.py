"""Consistent-hash ring over reduction fingerprints (ISSUE 14 tentpole).

The fleet front door (blit/serve/fleet.py) routes every product request
to a stable OWNER peer plus ``replicas - 1`` successor peers.  Keys are
the PR-3 content-addressed reduction fingerprints — order-insensitive
over the raw members and every output-affecting knob — so two front
doors (or two processes of one door across restarts) agree on ownership
without coordination, and cross-host dedupe is structural: the same
product always lands on the same owner's cache.

Design points:

- **Hashes are sha256**, never Python ``hash()``: ``PYTHONHASHSEED``
  randomizes the latter per process, and ring agreement ACROSS processes
  is the whole point (pinned by tests/test_fleet_ring.py's subprocess
  determinism drill).
- **Virtual nodes** (``vnodes`` per peer) smooth the load spread: with
  the default 128 vnodes a peer's share of a large keyspace stays within
  a small factor of fair (the uniform-spread invariant test bounds it).
- **Minimal movement**: removing a peer moves ONLY the keys it owned
  (≈ K/N of K keys over N peers) onto their next successors; adding one
  moves only the keys it now owns.  Everything else stays put — a
  rolling restart must not invalidate the whole fleet's cache.
- **Replica sets never collapse**: ``owners(key, n)`` walks the ring
  clockwise collecting DISTINCT peers, so a replica set has ``min(n,
  peers)`` different hosts however the vnodes interleave.

The ring itself is pure data (stdlib only, thread-safe); liveness —
ejecting a dead peer, rejoining a recovered one — is the front door's
job (:class:`blit.serve.fleet.FleetFrontDoor`), which calls
:meth:`remove` / :meth:`add` off its lease watch.
"""

from __future__ import annotations

import bisect
import hashlib
import threading
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

__all__ = ["HashRing", "ring_hash"]


def ring_hash(key: str) -> int:
    """A 64-bit ring position for ``key`` — the top 8 bytes of its
    sha256, so positions are stable across processes and platforms."""
    return int.from_bytes(
        hashlib.sha256(key.encode("utf-8")).digest()[:8], "big")


class HashRing:
    """Consistent-hash ring mapping keys to ordered distinct peer sets.

    ``peers`` seeds the ring; ``vnodes`` is the virtual-node count per
    peer (spread smoothness); ``replicas`` is the DEFAULT owner-set size
    :meth:`owners` returns.  All methods are thread-safe.
    """

    def __init__(self, peers: Iterable[str] = (), *, vnodes: int = 128,
                 replicas: int = 2):
        self.vnodes = max(1, int(vnodes))
        self.replicas = max(1, int(replicas))
        self._lock = threading.Lock()
        self._peers: Dict[str, bool] = {}
        # Sorted parallel arrays: vnode position -> owning peer.
        self._points: List[int] = []
        self._owners: List[str] = []
        for p in peers:
            self.add(p)

    # -- membership --------------------------------------------------------
    def _vnode_points(self, peer: str) -> List[int]:
        return [ring_hash(f"{peer}#{v}") for v in range(self.vnodes)]

    def add(self, peer: str) -> bool:
        """Join ``peer`` (idempotent).  Returns True when it was new."""
        with self._lock:
            if peer in self._peers:
                return False
            self._peers[peer] = True
            for pt in self._vnode_points(peer):
                i = bisect.bisect(self._points, pt)
                self._points.insert(i, pt)
                self._owners.insert(i, peer)
            return True

    def remove(self, peer: str) -> bool:
        """Leave ``peer`` (idempotent).  Returns True when it was
        present.  Only the keys it owned move — to their next clockwise
        successor — which is the minimal-movement contract."""
        with self._lock:
            if peer not in self._peers:
                return False
            del self._peers[peer]
            keep = [(pt, o) for pt, o in zip(self._points, self._owners)
                    if o != peer]
            self._points = [pt for pt, _ in keep]
            self._owners = [o for _, o in keep]
            return True

    def peers(self) -> List[str]:
        with self._lock:
            return sorted(self._peers)

    def __len__(self) -> int:
        with self._lock:
            return len(self._peers)

    def __contains__(self, peer: str) -> bool:
        with self._lock:
            return peer in self._peers

    # -- lookup ------------------------------------------------------------
    def owners(self, key: str, n: Optional[int] = None,
               exclude: Sequence[str] = ()) -> List[str]:
        """The ordered DISTINCT owner set for ``key``: the first peer is
        the owner, the rest its failover/hedge replicas, clockwise from
        the key's ring position.  ``n`` defaults to the ring's
        ``replicas``; fewer peers than ``n`` returns them all.
        ``exclude`` skips peers (an ejected-but-not-yet-removed host)."""
        want = self.replicas if n is None else max(1, int(n))
        skip = set(exclude)
        with self._lock:
            if not self._points:
                return []
            out: List[str] = []
            seen = set(skip)
            start = bisect.bisect(self._points, ring_hash(key))
            m = len(self._points)
            for step in range(m):
                peer = self._owners[(start + step) % m]
                if peer in seen:
                    continue
                seen.add(peer)
                out.append(peer)
                if len(out) >= want:
                    break
            return out

    def owner(self, key: str) -> Optional[str]:
        got = self.owners(key, 1)
        return got[0] if got else None

    # -- diagnostics -------------------------------------------------------
    def spread(self, keys: Iterable[str]) -> Dict[str, int]:
        """How many of ``keys`` each peer owns — the uniform-load
        invariant's measurement (tests) and ``fleet stats``' ring row."""
        counts = {p: 0 for p in self.peers()}
        for k in keys:
            o = self.owner(k)
            if o is not None:
                counts[o] += 1
        return counts

    def moved(self, keys: Iterable[str], other: "HashRing"
              ) -> Tuple[int, int]:
        """``(moved, total)`` keys whose OWNER differs between this ring
        and ``other`` — the minimal-key-movement invariant's
        measurement."""
        moved = total = 0
        for k in keys:
            total += 1
            if self.owner(k) != other.owner(k):
                moved += 1
        return moved, total

    # -- resize deltas (ISSUE 17) ------------------------------------------
    def incoming_keys(self, joiner: str,
                      keys: Iterable[str]) -> List[str]:
        """The subset of ``keys`` whose ownership would MOVE to
        ``joiner`` if it joined now — the scale-out warm-handoff range.
        Pure: computed on a shadow ring, this ring is not mutated.  By
        minimal movement these are the ONLY keys that move, so warming
        exactly this range makes the membership flip hit-rate neutral.
        A ``joiner`` already present owns its current keys."""
        members = self.peers()
        if joiner not in members:
            members.append(joiner)
        shadow = HashRing(members, vnodes=self.vnodes,
                          replicas=self.replicas)
        return [k for k in keys if shadow.owner(k) == joiner]

    def departing_keys(self, leaver: str,
                       keys: Iterable[str]) -> List[str]:
        """The subset of ``keys`` ``leaver`` currently owns — exactly
        what moves to the clockwise successors when it leaves (the
        scale-in pre-warm range)."""
        return [k for k in keys if self.owner(k) == leaver]
