"""SLO-driven elastic fleet membership (ISSUE 17 tentpole).

The fleet serve plane (blit/serve/fleet.py) survives peer death and the
SLO plane (blit/monitor.py) knows when the fleet is melting — but
capacity was a fixed N: the front door could shed load, never add it.
:class:`FleetController` closes that loop, the way the BL@GBT archive
deployment this repo reproduces rides its diurnal observing/release
cycle:

- **Scale-out**: standby peers (``blit fleet-peer --standby`` — process
  up, lease beating, NOT in the ring) are admitted when the burn-rate
  evaluator pages, but only after a **warm handoff**.  The controller
  computes the joiner's incoming key range from the ring delta
  (:meth:`~blit.serve.ring.HashRing.incoming_keys` — by minimal
  movement, the ONLY keys that move), streams the hot entries in
  exactly that range as ``/warm`` hints with a ``wait_s`` ack, and
  flips membership only once the joiner acks warm completion or the
  handoff deadline burns (fail-open: flip anyway — elastic capacity
  NOW beats a warm cache — counting ``elastic.warm_timeout``).
- **Scale-in**: sustained idle — ``idle_windows`` consecutive
  observation ticks under ``idle_rps`` — drains the COLDEST peer
  through the existing deadline-aware drain before retiring it from
  the ring; in-flight requests complete, the leaver's hot range is
  pre-warmed onto its successors, and its pooled keep-alives are
  severed (:meth:`~blit.serve.http.ConnectionPool.evict_peer`).
- **Flap guard**: any resize arms a ``hysteresis_s`` cooldown during
  which further actions are SUPPRESSED (counted
  ``elastic.flap_suppressed``) and the idle counter is reset by any
  page — so a page→idle→page cycle cannot thrash membership (pinned
  by tests/test_elastic.py's hysteresis drill).

While a flip is in progress the door's ``/healthz`` answers an honest
``"resizing"`` status (and :func:`blit.monitor.register_health_hook`
carries the same reason onto every publisher health document) — a
probe that reads "ok" mid-flip would route traffic on stale
membership.  ``elastic.*`` counters and histograms
(:data:`ELASTIC_HISTS`) ride the door's timeline onto ``/metrics`` and
``fleet stats``.

The controller is deliberately single-threaded per tick and mostly
pure over door state: drive :meth:`observe` from tests with a fake
clock, or :meth:`start` the background loop in a deployment.
:meth:`scale_out` / :meth:`scale_in` double as the manual-resize
surface the WORKFLOWS.md runbook reaches for when the operator knows
better than the evaluator.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Callable, Dict, List, Optional

from blit.config import DEFAULT, SiteConfig, elastic_defaults
from blit.observability import Timeline, flight_recorder
from blit.serve.http import http_json

log = logging.getLogger("blit.serve.elastic")

# The elastic plane's histograms (the FLEET_HISTS convention):
# resize_s is the whole flip — handoff included — per action;
# warm_bytes the product bytes the joiner completed during handoff.
ELASTIC_HISTS = ("elastic.resize_s", "elastic.warm_bytes")


class FleetController:
    """The burn-rate→membership loop (module docstring).

    ``door`` is the :class:`~blit.serve.fleet.FleetFrontDoor` whose
    ring this controller resizes; ``evaluator`` the
    :class:`~blit.monitor.BurnRateEvaluator` whose pages trigger
    scale-out (None = manual/idle-only).  ``feed``, when set to a
    :class:`~blit.observability.Timeline` (usually the door's), makes
    the controller feed the evaluator that timeline's per-tick deltas —
    leave it None when a MetricsPublisher already owns the evaluator's
    diet, or the same interval would be counted twice.  ``terminate``
    is an optional ``(peer_name) -> None`` callable run after a
    scale-in flip — the CLI rig passes SIGTERM-the-child here, matching
    the deadline-aware drain handler peers install."""

    def __init__(self, door, evaluator=None, *,
                 config: SiteConfig = DEFAULT,
                 timeline: Optional[Timeline] = None,
                 feed: Optional[Timeline] = None,
                 terminate: Optional[Callable[[str], None]] = None,
                 idle_rps: Optional[float] = None,
                 idle_windows: Optional[int] = None,
                 hysteresis_s: Optional[float] = None,
                 warm_timeout_s: Optional[float] = None,
                 warm_hints: Optional[int] = None,
                 min_peers: Optional[int] = None,
                 poll_s: Optional[float] = None,
                 drain_timeout_s: Optional[float] = None,
                 clock: Callable[[], float] = time.monotonic):
        d = elastic_defaults(config)
        self.door = door
        self.evaluator = evaluator
        # Default onto the DOOR's timeline so elastic.* counters land
        # on the same /metrics and `fleet stats` surface as fleet.*.
        self.timeline = timeline if timeline is not None else door.timeline
        self.idle_rps = float(idle_rps if idle_rps is not None
                              else d["idle_rps"])
        self.idle_windows = int(idle_windows if idle_windows is not None
                                else d["idle_windows"])
        self.hysteresis_s = float(hysteresis_s if hysteresis_s is not None
                                  else d["hysteresis_s"])
        self.warm_timeout_s = float(
            warm_timeout_s if warm_timeout_s is not None
            else d["warm_timeout_s"])
        self.warm_hints = int(warm_hints if warm_hints is not None
                              else d["warm_hints"])
        self.min_peers = int(min_peers if min_peers is not None
                             else d["min_peers"])
        self.poll_s = float(poll_s if poll_s is not None else d["poll_s"])
        self.drain_timeout_s = float(
            drain_timeout_s if drain_timeout_s is not None
            else d["drain_timeout_s"])
        self.clock = clock
        self._feed = feed
        self._feed_state: Optional[Dict] = None
        self._terminate = terminate
        self._lock = threading.Lock()
        self._resizing: Optional[str] = None
        self._cooldown_until = 0.0
        self._idle_ticks = 0
        self._last_tick: Optional[float] = None
        self._last_requests = self._requests_total()
        self._actions: List[Dict] = []
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # The honest-health satellite: every publisher health document
        # in this process carries the resize phase while a flip runs.
        from blit import monitor

        monitor.register_health_hook("elastic", self._health_hook)

    # -- the observation tick ----------------------------------------------
    def observe(self, interval_s: Optional[float] = None
                ) -> Optional[Dict]:
        """One controller tick (the loop's body; tests and the diurnal
        bench drive it directly): feed the evaluator, judge paging vs
        idle, and resize — unless the flap guard is armed.  Returns the
        action record when a resize happened, else None."""
        now = self.clock()
        if interval_s is not None:
            dt = float(interval_s)
        elif self._last_tick is not None:
            dt = now - self._last_tick
        else:
            dt = self.poll_s
        dt = max(dt, 1e-9)
        self._last_tick = now
        if self._feed is not None and self.evaluator is not None:
            from blit.monitor import _delta_timeline

            delta = _delta_timeline(self._feed, self._feed_state)
            self._feed_state = self._feed.state()
            self.evaluator.observe(delta, dt)
        paging = bool(self.evaluator.breached()) if self.evaluator else False
        reqs = self._requests_total()
        rps = max(0, reqs - self._last_requests) / dt
        self._last_requests = reqs
        if paging or rps > self.idle_rps:
            # Any page — or any real traffic — resets the idle run:
            # scale-in needs SUSTAINED idle, never one quiet tick.
            self._idle_ticks = 0
        else:
            self._idle_ticks += 1
        guarded = now < self._cooldown_until
        if paging and self._pick_standby() is not None:
            if guarded:
                self.timeline.count("elastic.flap_suppressed")
                return None
            return self.scale_out()
        if (self._idle_ticks >= self.idle_windows
                and len(self.door.ring) > self.min_peers):
            if guarded:
                self.timeline.count("elastic.flap_suppressed")
                return None
            self._idle_ticks = 0
            return self.scale_in()
        return None

    def _requests_total(self) -> int:
        row = self.door.timeline.report().get("fleet.requests")
        return int(row["calls"]) if isinstance(row, dict) else 0

    # -- scale-out ---------------------------------------------------------
    def scale_out(self, name: Optional[str] = None) -> Optional[Dict]:
        """Admit one standby after a warm handoff (also the manual
        "the fleet is melting" lever).  ``name`` picks the standby
        (default: first lease-fresh one); returns the action record, or
        None when no admissible standby exists."""
        cand = name if name is not None else self._pick_standby()
        if cand is None:
            return None
        t0 = self.clock()
        self._set_resizing(f"scale-out:{cand}")
        try:
            warm = self._warm_handoff(cand)
            self.door.admit_peer(cand)
        finally:
            self._set_resizing(None)
            self._arm_guard()
        dt = self.clock() - t0
        self.timeline.count("elastic.scale_out")
        self.timeline.observe("elastic.resize_s", dt)
        flight_recorder().event("elastic", "scale_out", peer=cand,
                                hinted=warm["hinted"],
                                completed=warm["completed"],
                                acked=warm["acked"])
        rec = {"action": "scale-out", "peer": cand,
               "resize_s": round(dt, 6), **warm}
        with self._lock:
            self._actions.append(rec)
        log.warning("elastic: scaled OUT %s (%d/%d warm hints "
                    "completed%s)", cand, warm["completed"],
                    warm["hinted"], "" if warm["acked"]
                    else "; handoff timed out, flipped fail-open")
        return rec

    def _warm_handoff(self, joiner: str) -> Dict:
        """Stream the joiner's incoming hot range and wait for its ack:
        the ring delta names exactly the keys that will move, the
        range-scoped hints carry their recipes, and ``wait_s`` makes
        the ``/warm`` answer a completion ack the flip gates on."""
        hints = self.door.warm_hints(limit=self.warm_hints)
        incoming = set(self.door.ring.incoming_keys(
            joiner, [fp for fp, _ in hints]))
        recipes = [r for fp, r in hints if fp in incoming]
        out = {"hinted": len(recipes), "completed": 0, "warm_bytes": 0,
               "acked": True}
        if not recipes:
            return out
        url = self.door._peers[joiner].url
        try:
            status, _, body = http_json(
                "POST", url, "/warm",
                {"recipes": recipes, "wait_s": self.warm_timeout_s,
                 "priority": 2},
                timeout=self.warm_timeout_s + 10.0, pool=self.door.pool)
            doc = body if isinstance(body, dict) else {}
            out["completed"] = int(doc.get("completed", 0) or 0)
            out["warm_bytes"] = int(doc.get("bytes", 0) or 0)
            out["acked"] = (
                status == 202 and not doc.get("timed_out")
                and out["completed"] + int(doc.get("rejected", 0) or 0)
                >= len(recipes))
        except OSError:
            out["acked"] = False
        if out["warm_bytes"]:
            self.timeline.observe("elastic.warm_bytes",
                                  float(out["warm_bytes"]))
        if not out["acked"]:
            # Fail-open (the tentpole contract): a cold joiner serving
            # is strictly better than a paging fleet waiting on warmth.
            self.timeline.count("elastic.warm_timeout")
        return out

    def _pick_standby(self) -> Optional[str]:
        for nm, p in sorted(self.door._peers.items()):
            if p.standby and p.watch.fresh():
                return nm
        return None

    # -- scale-in ----------------------------------------------------------
    def scale_in(self, name: Optional[str] = None) -> Optional[Dict]:
        """Drain and retire one peer (also the manual "the fleet is
        idle" lever).  ``name`` picks the leaver (default: the coldest
        in-ring peer by hot-entry ownership); refuses to go below
        ``min_peers``.  In-flight requests complete inside the drain
        deadline; the leaver's hot range is pre-warmed onto its
        successors; its pooled sockets are severed by
        :meth:`~blit.serve.fleet.FleetFrontDoor.retire_peer`."""
        if name is None:
            victim = self._pick_coldest()
        else:
            victim = name if len(self.door.ring) > self.min_peers else None
        if victim is None:
            return None
        t0 = self.clock()
        self._set_resizing(f"scale-in:{victim}")
        try:
            hinted = self._prewarm_successors(victim)
            drained = self._drain_leaver(victim)
            self.door.retire_peer(victim)
            if self._terminate is not None:
                try:
                    self._terminate(victim)
                except Exception:  # noqa: BLE001 — the flip already won
                    log.warning("elastic: terminate(%s) failed", victim,
                                exc_info=True)
        finally:
            self._set_resizing(None)
            self._arm_guard()
        dt = self.clock() - t0
        self.timeline.count("elastic.scale_in")
        self.timeline.observe("elastic.resize_s", dt)
        flight_recorder().event("elastic", "scale_in", peer=victim,
                                drained=drained, hinted=hinted)
        rec = {"action": "scale-in", "peer": victim, "drained": drained,
               "hinted": hinted, "resize_s": round(dt, 6)}
        with self._lock:
            self._actions.append(rec)
        log.warning("elastic: scaled IN %s (drained=%s, %d hot hints "
                    "handed to successors)", victim, drained, hinted)
        return rec

    def _pick_coldest(self) -> Optional[str]:
        members = self.door.ring.peers()
        if len(members) <= self.min_peers:
            return None
        heat = {nm: 0 for nm in members}
        with self.door._lock:
            hot = list(self.door._hot.items())
        for fp, (hits, _) in hot:
            o = self.door.ring.owner(fp)
            if o in heat:
                heat[o] += hits
        return min(sorted(heat), key=lambda nm: heat[nm])

    def _prewarm_successors(self, victim: str) -> int:
        """Hand the leaver's hot range to its clockwise successors
        BEFORE the drain — the drain-hint machinery aimed at exactly
        the departing keys, so retiring the peer degrades nothing."""
        hints = self.door.warm_hints(limit=self.warm_hints)
        departing = set(self.door.ring.departing_keys(
            victim, [fp for fp, _ in hints]))
        per_peer: Dict[str, List[Dict]] = {}
        for fp, recipe in hints:
            if fp not in departing:
                continue
            heirs = self.door.ring.owners(fp, exclude=(victim,))
            if heirs:
                per_peer.setdefault(heirs[0], []).append(recipe)
        sent = 0
        for nm, recipes in per_peer.items():
            try:
                http_json("POST", self.door._peers[nm].url, "/warm",
                          {"recipes": recipes}, timeout=5.0,
                          pool=self.door.pool)
                sent += len(recipes)
            except OSError:
                pass  # best-effort, like every warm
        return sent

    def _drain_leaver(self, victim: str) -> bool:
        """Deadline-bounded graceful drain: tell the peer to refuse new
        work, then poll its in-flight count to zero.  An unreachable
        peer is as drained as it gets — the flip proceeds."""
        url = self.door._peers[victim].url
        deadline = time.monotonic() + self.drain_timeout_s
        try:
            http_json("POST", url, "/drain", {}, timeout=5.0,
                      pool=self.door.pool)
        except OSError:
            return False
        while time.monotonic() < deadline:
            try:
                st, _, body = http_json("GET", url, "/stats",
                                        timeout=2.0, pool=self.door.pool)
            except OSError:
                return False
            if st != 200 or not isinstance(body, dict):
                return False
            if int(body.get("inflight", 0) or 0) <= 0:
                return True
            time.sleep(min(0.2, max(0.01, self.poll_s / 5)))
        log.warning("elastic: drain of %s timed out after %.1fs",
                    victim, self.drain_timeout_s)
        return False

    # -- flap guard / health -----------------------------------------------
    def _arm_guard(self) -> None:
        self._cooldown_until = self.clock() + self.hysteresis_s
        self.timeline.gauge("elastic.cooldown_s", self.hysteresis_s)

    def _guard_remaining(self) -> float:
        return max(0.0, self._cooldown_until - self.clock())

    def _set_resizing(self, reason: Optional[str]) -> None:
        with self._lock:
            self._resizing = reason
        self.door.resize_reason = reason
        self.timeline.gauge("elastic.resizing",
                            0.0 if reason is None else 1.0)

    def _health_hook(self) -> Dict:
        with self._lock:
            reason = self._resizing
        if reason:
            return {"degraded": True, "reason": reason,
                    "status": "resizing"}
        return {"degraded": False,
                "cooldown_s": round(self._guard_remaining(), 3)}

    # -- surfaces / lifecycle ----------------------------------------------
    def stats(self) -> Dict:
        with self._lock:
            resizing = self._resizing
            actions = list(self._actions[-16:])
        return {
            "resizing": resizing,
            "cooldown_s": round(self._guard_remaining(), 3),
            "idle_ticks": self._idle_ticks,
            "idle_windows": self.idle_windows,
            "min_peers": self.min_peers,
            "standbys": [nm for nm, p in sorted(self.door._peers.items())
                         if p.standby],
            "actions": actions,
        }

    def start(self) -> "FleetController":
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._loop, name="blit-elastic", daemon=True)
            self._thread.start()
        return self

    def _loop(self) -> None:
        while not self._stop.wait(self.poll_s):
            try:
                self.observe()
            except Exception:  # noqa: BLE001 — the loop must not die
                log.warning("elastic tick failed", exc_info=True)

    def close(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
            self._thread = None
        from blit import monitor

        monitor.unregister_health_hook("elastic")

    def __enter__(self) -> "FleetController":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.close()


__all__ = ["ELASTIC_HISTS", "FleetController"]
