"""Archive catalog: the session/scan/product index of a data tree
(ISSUE 19 tentpole #1).

The reference package is an *archive access layer* — inventory the
fleet, then load scan products by (session, scan) — but until this
module every fleet request had to spell out explicit member paths.
:class:`CatalogIndex` closes that gap: an in-RAM index built from the
existing :func:`blit.inventory.get_inventory` crawl and the
:mod:`blit.naming` grammar, held per process and kept fresh by
**mtime-invalidated incremental rescan** — each session directory's
subtree signature (the sorted ``(relative dir, mtime_ns)`` pairs; adding
or removing a file touches its directory's mtime) is recorded at crawl
time, and a later refresh re-crawls ONLY the sessions whose signature
changed.  A bounded TTL'd **negative-lookup cache** keeps repeated
misses from forcing a rescan per ask.

Two serving surfaces ride the fleet plane unchanged:

- peers serve the catalog document as ``ProductRequest(kind="catalog")``
  over the existing product wire (``raw`` carries the query string:
  ``""`` lists sessions, ``"<session>"`` one session's scans,
  ``"<session>/<scan>"`` one scan's membership);
- the front door resolves by-(session, scan) product asks into the
  explicit member-path recipe BEFORE ring routing
  (:meth:`CatalogIndex.resolve`), so a logical ask and the equivalent
  explicit-path ask fingerprint identically — same ring owner, same
  single-flight group, byte-identical product.

Import discipline matches the serve plane: stdlib at module scope,
blit imports lazy inside methods.
"""

from __future__ import annotations

import hashlib
import os
import re
import threading
import time
from collections import OrderedDict
from typing import Dict, List, Optional, Tuple

from blit.config import DEFAULT, SiteConfig, catalog_defaults

# Catalog crawls index BOTH the derivable source (``.NNNN.raw`` scan
# sequences — what :meth:`CatalogIndex.resolve` turns into member-path
# recipes) and the already-derived rawspec products sitting next to
# them (listed per scan under ``"products"``).
CATALOG_FILE_RE = re.compile(r"(\.\d{4}\.raw|\.rawspec\.\d{4}\.(?:h5|fil))$")


class CatalogMiss(KeyError):
    """An ask for a session/scan the catalog does not hold (after a
    forced rescan) — the door maps it onto its request-error surface."""


def catalog_fingerprint(query: str) -> str:
    """The content address of one catalog ask — what the front door
    routes/dedupes catalog requests by.  Product fingerprints hash raw
    bytes identity; a catalog document's identity is its QUERY (the
    answer changes as the tree grows, exactly like a directory
    listing), so identical asks land on one ring owner and coalesce
    while never colliding with any product key."""
    return hashlib.sha256(f"blit.catalog:{query}".encode()).hexdigest()


class CatalogIndex:
    """In-RAM session/scan/product index over one archive root (module
    docstring).  All methods are thread-safe.  ``rescan_s`` bounds how
    often a lookup may re-stat the tree (0 = every lookup);
    ``negative_ttl_s`` / ``negative_max`` bound the negative cache."""

    def __init__(
        self,
        root: Optional[str] = None,
        *,
        config: SiteConfig = DEFAULT,
        rescan_s: Optional[float] = None,
        negative_ttl_s: Optional[float] = None,
        negative_max: Optional[int] = None,
        timeline=None,
    ):
        kn = catalog_defaults(config)
        self.root = os.path.abspath(root if root is not None
                                    else (kn["root"] or config.root))
        self.config = config
        self.rescan_s = (kn["rescan_s"] if rescan_s is None
                         else float(rescan_s))
        self.negative_ttl_s = (kn["negative_ttl_s"] if negative_ttl_s is None
                               else float(negative_ttl_s))
        self.negative_max = max(1, int(kn["negative_max"]
                                       if negative_max is None
                                       else negative_max))
        self.timeline = timeline
        self._lock = threading.Lock()
        # session -> {"sig": ((reldir, mtime_ns), ...), "scans": {...}}
        self._sessions: Dict[str, Dict] = {}
        # (session, scan-or-None) -> monotonic expiry of the miss.
        self._neg: "OrderedDict[Tuple[str, Optional[str]], float]" = (
            OrderedDict())
        self._last_refresh = float("-inf")
        self._generation = 0
        self.counts: Dict[str, int] = {
            "lookups": 0, "hits": 0, "misses": 0, "neg_hits": 0,
            "rescans": 0, "refreshes": 0,
        }

    # -- counters ----------------------------------------------------------
    def _count(self, name: str, n: int = 1) -> None:
        self.counts[name] = self.counts.get(name, 0) + n
        if self.timeline is not None:
            self.timeline.count(f"catalog.{name}", n)

    def stats(self) -> Dict[str, int]:
        with self._lock:
            out = dict(self.counts)
            out["sessions"] = len(self._sessions)
            out["scans"] = sum(len(s["scans"])
                               for s in self._sessions.values())
            out["negative_entries"] = len(self._neg)
            out["generation"] = self._generation
        return out

    # -- crawl / refresh ---------------------------------------------------
    def _tree_sig(self, session_dir: str) -> Tuple:
        """The mtime signature of one session subtree: sorted
        ``(relative dir, mtime_ns)`` pairs over every directory under
        it.  Creating/removing a file updates its parent directory's
        mtime, so ANY membership change flips the signature — file
        stats are only paid for sessions whose signature moved."""
        sig: List[Tuple[str, int]] = []
        for dirpath, dirnames, _files in os.walk(session_dir,
                                                 followlinks=True):
            dirnames.sort()
            try:
                st = os.stat(dirpath)
            except OSError:
                continue
            sig.append((os.path.relpath(dirpath, session_dir),
                        st.st_mtime_ns))
        return tuple(sorted(sig))

    def _crawl_session(self, session: str) -> Dict:
        """One session's scan table via the EXISTING inventory crawl
        (``get_inventory`` anchored to exactly this session — the
        corrected ``PLAYER_RE`` and warn-and-skip parse rules apply
        unchanged, so malformed player dirs never index)."""
        from blit import inventory, naming

        records = inventory.get_inventory(
            CATALOG_FILE_RE,
            root=self.root,
            session_re=re.compile(rf"^{re.escape(session)}$"),
            extra=self.config.extra,
            player_re=self.config.player_re,
            config=self.config,
        )
        scans: Dict[str, Dict] = {}
        raw_records = []
        for r in records:
            sc = scans.setdefault(r.scan, {
                "src": r.src_name, "imjd": r.imjd, "smjd": r.smjd,
                "bands": set(), "banks": set(), "products": set(),
                "sequences": {},
            })
            sc["bands"].add(r.band)
            sc["banks"].add(r.bank)
            parsed = naming.parse_rawspec_name(r.file)
            if parsed is not None and parsed.product is not None:
                sc["products"].add(parsed.product)
            else:
                raw_records.append(r)
        for rec, paths in inventory.raw_sequences(raw_records):
            scans[rec.scan]["sequences"][(rec.band, rec.bank)] = paths
        return scans

    def refresh(self, force: bool = False) -> int:
        """Re-stat the tree and re-crawl the sessions whose subtree
        signature changed (all of them on first touch).  Rate-limited
        by ``rescan_s`` unless forced.  Returns how many sessions were
        (re)crawled."""
        from blit.inventory import _listdirs

        now = time.monotonic()
        with self._lock:
            if not force and now - self._last_refresh < self.rescan_s:
                return 0
            self._last_refresh = now
            known = {s: e["sig"] for s, e in self._sessions.items()}
        self._count("refreshes")
        session_re = self.config.session_re
        live = [s for s in _listdirs(self.root) if session_re.search(s)]
        fresh: Dict[str, Dict] = {}
        rescanned = 0
        for session in live:
            sig = self._tree_sig(os.path.join(self.root, session))
            if session in known and known[session] == sig:
                continue
            fresh[session] = {"sig": sig,
                              "scans": self._crawl_session(session)}
            rescanned += 1
        with self._lock:
            gone = set(self._sessions) - set(live)
            for s in gone:
                del self._sessions[s]
            self._sessions.update(fresh)
            if fresh or gone:
                self._generation += 1
        if rescanned:
            self._count("rescans", rescanned)
        return rescanned

    # -- negative cache ----------------------------------------------------
    def _neg_fresh_locked(self, key: Tuple[str, Optional[str]]) -> bool:
        exp = self._neg.get(key)
        if exp is None:
            return False
        if time.monotonic() >= exp:
            del self._neg[key]
            return False
        return True

    def _neg_note_locked(self, key: Tuple[str, Optional[str]]) -> None:
        self._neg[key] = time.monotonic() + self.negative_ttl_s
        self._neg.move_to_end(key)
        while len(self._neg) > self.negative_max:
            self._neg.popitem(last=False)

    # -- lookups -----------------------------------------------------------
    def _find_locked(self, session: Optional[str],
                     scan: Optional[str]) -> Optional[Dict]:
        if session is None:
            return {"_all": True}
        entry = self._sessions.get(session)
        if entry is None:
            return None
        if scan is None:
            return entry
        return entry["scans"].get(scan)

    def lookup(self, session: Optional[str] = None,
               scan: Optional[str] = None) -> Dict:
        """The catalog document for one ask (module docstring's three
        shapes).  A miss forces ONE rescan (the data may have just
        landed) and then raises :class:`CatalogMiss`; the negative
        cache answers repeat misses without touching the tree until
        the TTL expires."""
        self._count("lookups")
        key = (session or "", scan)
        with self._lock:
            if session is not None and self._neg_fresh_locked(key):
                self._count("neg_hits")
                self._count("misses")
                raise CatalogMiss(
                    f"no such {'scan' if scan else 'session'}: "
                    f"{session}{'/' + scan if scan else ''} "
                    "(negative-cached)")
        self.refresh()
        with self._lock:
            found = self._find_locked(session, scan)
        if found is None:
            self.refresh(force=True)
            with self._lock:
                found = self._find_locked(session, scan)
                if found is None:
                    self._neg_note_locked(key)
                    self._count("misses")
                    raise CatalogMiss(
                        f"no such {'scan' if scan else 'session'}: "
                        f"{session}{'/' + scan if scan else ''}")
        with self._lock:
            self._neg.pop(key, None)
            self._count("hits")
            return self._render_locked(session, scan)

    def _render_locked(self, session: Optional[str],
                       scan: Optional[str]) -> Dict:
        """JSON-able view of one ask (under the lock; pure reads)."""
        if session is None:
            return {
                "root": self.root, "generation": self._generation,
                "sessions": {
                    s: {"scans": len(e["scans"]),
                        "files": sum(
                            len(sc["products"])
                            + sum(len(p) for p in
                                  sc["sequences"].values())
                            for sc in e["scans"].values())}
                    for s, e in sorted(self._sessions.items())
                },
            }
        entry = self._sessions[session]
        if scan is None:
            return {
                "root": self.root, "session": session,
                "generation": self._generation,
                "scans": {
                    name: self._scan_doc(sc, members=False)
                    for name, sc in sorted(entry["scans"].items())
                },
            }
        return {
            "root": self.root, "session": session, "scan": scan,
            "generation": self._generation,
            **self._scan_doc(entry["scans"][scan], members=True),
        }

    @staticmethod
    def _scan_doc(sc: Dict, members: bool) -> Dict:
        doc = {
            "src": sc["src"], "imjd": sc["imjd"], "smjd": sc["smjd"],
            "bands": sorted(sc["bands"]), "banks": sorted(sc["banks"]),
            "products": sorted(sc["products"]),
            "sequences": len(sc["sequences"]),
        }
        if members:
            doc["members"] = {
                f"{band}{bank}": list(paths)
                for (band, bank), paths in sorted(sc["sequences"].items())
            }
        return doc

    def resolve(self, session: str, scan: str, *,
                band: Optional[int] = None,
                bank: Optional[int] = None) -> List[str]:
        """The member-path list of one (session, scan)'s RAW sequence —
        what the front door substitutes into a logical product ask
        before ring routing.  A scan recorded by several players needs
        ``band``/``bank`` to pick one; an ambiguous ask is a loud
        :class:`CatalogMiss` (guessing a recording would serve the
        wrong bytes)."""
        self.lookup(session, scan)
        with self._lock:
            seqs = self._sessions[session]["scans"][scan]["sequences"]
            picks = {
                k: v for k, v in seqs.items()
                if (band is None or k[0] == band)
                and (bank is None or k[1] == bank)
            }
        if not picks:
            raise CatalogMiss(
                f"{session}/{scan}: no RAW sequence"
                + (f" for player BLP{band}{bank}"
                   if band is not None or bank is not None else ""))
        if len(picks) > 1:
            players = ", ".join(f"BLP{b}{k}" for b, k in sorted(picks))
            raise CatalogMiss(
                f"{session}/{scan} has {len(picks)} RAW sequences "
                f"({players}); pass band=/bank= to pick one")
        return list(next(iter(picks.values())))

    # -- the kind="catalog" serving surface --------------------------------
    def serve(self, query: str) -> Tuple[Dict, "object"]:
        """Answer one wire catalog ask: ``(header, empty array)`` in the
        product-result shape, so the existing encode/decode wire and
        the peer's ticket plumbing carry it unchanged.  The document
        rides the header."""
        import numpy as np

        query = (query or "").strip("/")
        session: Optional[str] = None
        scan: Optional[str] = None
        if query:
            session, _, scan_part = query.partition("/")
            scan = scan_part or None
        doc = self.lookup(session, scan)
        header = {"kind": "catalog", "query": query, **doc}
        data = np.zeros((0, 1, 0), np.float32)
        data.setflags(write=False)
        return header, data
