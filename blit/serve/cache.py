"""Two-tier content-addressed product cache (the serving layer's artifact
store, ISSUE 3 tentpole).

Keys are **reduction fingerprints**: a stable digest over the raw-input
identity (the order-insensitive ``(path, size, mtime_ns)`` member triples of
:meth:`blit.pipeline.ReductionCursor.normalized_members`) plus the full
output-affecting reducer configuration.  Two callers asking for the same
product of the same bytes — however their globs ordered the ``.NNNN.raw``
members — get the same key; touching a member or changing any knob gets a
different one.  Content addressing makes invalidation structural: a stale
entry is simply never asked for again.

Tiers:

- **RAM** — an LRU dict of finished ``(header, product array)`` pairs,
  bounded by a byte budget (``SiteConfig.cache_ram_bytes``).  Entries are
  published complete-and-read-only under the cache lock, so a concurrent
  reader sees a whole product or a miss — never a torn entry (eviction
  drops the dict reference; an array already handed out stays valid).
- **Disk** — completed FBH5 products (+ a JSON header sidecar) under one
  directory, indexed by fingerprint.  Publish is atomic: both files are
  written to temp names and ``os.replace``d into place, data before
  sidecar, so the sidecar's existence marks a complete entry exactly like
  the pipeline's ``.partial``-rename rule.  Loads re-probe the entry with
  :func:`blit.io.fbh5.resume_target_ok`; an unreadable/corrupt entry (torn
  by a crash mid-publish on a non-atomic filesystem, bit rot) is EVICTED
  and reported as a miss instead of raising.

A third, derived tier rides both (ISSUE 16 tentpole #3): the
**encoded wire body** — the already-framed ``application/x-blit-product``
bytes of an entry (:func:`blit.serve.http.encode_product_wire`).  A hot
binary-wire hit is then one memoryview write: no re-encode, no ndarray
copy, and a disk-tier wire hit streams file bytes without materializing
the array at all.  Wire bodies share the RAM byte budget but are always
evicted FIRST (they are re-derivable from their product), and the disk
form (``<fp>.wire`` = frame + CRC32 footer) is verified on load exactly
like the product files (PR 12).

A **cold tier** (ISSUE 19 tentpole #2) sits behind the hot disk tier
when ``cold_dir`` is set: an object-store-style content-addressed
layout (``<cold>/<fp[:2]>/<fp>.h5`` + the same meta sidecar, so ``blit
fsck`` walks it with the rules it already knows).  Hot-tier capacity
evictions DEMOTE into it (files moved, not copied — the bytes that
were verified at publish stay the bytes served later) and a cold hit
is PROMOTED back to hot under the PR-12 CRC manifest check before it
is served — a rotted cold entry is evicted and reported as a miss,
never promoted.  A cold miss falls through to the serve layer's
re-derivation path (the recipe in the meta sidecar — the ``tier ∈
{ram, wire, disk, cold, derive}`` story on ``/metrics``).

Hit/miss/evict counters land on the :class:`~blit.observability.Timeline`
(``cache.hit.ram`` / ``cache.hit.disk`` / ``cache.hit.wire`` /
``cache.hit.cold`` / ``cache.miss`` / ``cache.evict.*`` /
``cache.demote.cold`` / ``cache.promote.cold`` / ``cache.derive``) and
the ``cache.publish`` fault-injection point covers the disk publish
path for drills (blit/faults.py).
"""

from __future__ import annotations

import hashlib
import json
import logging
import os
import shutil
import threading
import time
import zlib
from collections import OrderedDict
from typing import Dict, Optional, Sequence, Tuple, Union

import numpy as np

from blit import faults
from blit.observability import Timeline

log = logging.getLogger("blit.serve.cache")


def reduction_fingerprint(
    raw_path: Union[str, Sequence[str]],
    *,
    nfft: int,
    nint: int,
    ntap: int = 4,
    stokes: str = "I",
    window: str = "hamming",
    fqav_by: int = 1,
    dtype: str = "float32",
    fft_method: str = "auto",
    extra: Optional[Dict] = None,
) -> str:
    """The content address of one reduction: sha256 over the canonical
    JSON of ``(raw identity, reducer config)``.

    The raw identity reuses :class:`blit.pipeline.ReductionCursor`'s
    ``(path, size, mtime_ns)`` member triples — the same
    "same config over the same bytes" contract the resume path enforces —
    normalized to an order-insensitive, absolute-path member list so cache
    keys are stable across glob orderings (ISSUE 3 satellite).  Raises
    ``OSError`` when a member does not exist: an address over unknown
    bytes would alias whatever lands at the path later.

    ``extra`` admits future key components (e.g. a despike width for mesh
    products) without breaking existing keys when absent.
    """
    from blit.pipeline import ReductionCursor

    paths = [raw_path] if isinstance(raw_path, str) else list(raw_path)
    paths = [os.path.abspath(p) for p in paths]
    sizes, mtimes = ReductionCursor.stat_raw(paths)
    ident = {
        "raw": ReductionCursor.normalized_members(paths, sizes, mtimes),
        "nfft": nfft, "ntap": ntap, "nint": nint, "stokes": stokes,
        "window": window, "fqav_by": fqav_by, "dtype": dtype,
        "fft_method": fft_method,
    }
    if extra:
        ident["extra"] = dict(sorted(extra.items()))
    blob = json.dumps(ident, sort_keys=True, default=str).encode()
    return hashlib.sha256(blob).hexdigest()


def fingerprint_for(reducer, raw_path: Union[str, Sequence[str]]) -> str:
    """The fingerprint of ``reducer`` (a :class:`blit.pipeline.RawReducer`
    or any reducer speaking its knob surface) applied to ``raw_path`` —
    pulls every output-affecting knob off the configured reducer so the
    two can never drift.  Reducers with EXTRA output-affecting knobs
    (e.g. :class:`blit.search.dedoppler.DedopplerReducer`'s drift-search
    parameters) expose them via a ``fingerprint_extra()`` dict, merged
    into the key the same way the despike width would be — absent for
    plain reductions, so existing keys are untouched."""
    extra_fn = getattr(reducer, "fingerprint_extra", None)
    return reduction_fingerprint(
        raw_path,
        nfft=reducer.nfft, nint=reducer.nint, ntap=reducer.ntap,
        stokes=reducer.stokes, window=reducer.window,
        fqav_by=reducer.fqav_by, dtype=reducer.dtype,
        fft_method=reducer.fft_method,
        extra=extra_fn() if extra_fn is not None else None,
    )


def _frozen(data: np.ndarray) -> np.ndarray:
    """A read-only float32 view of ``data`` the cache can hand to many
    concurrent callers: copied when the caller still holds a writable
    reference (a later mutation must not tear a served entry)."""
    data = np.asarray(data, np.float32)
    if data.flags.writeable:
        # A real copy, not ascontiguousarray (which returns the SAME
        # array when already contiguous — freezing it would flip the
        # caller's own buffer read-only).
        data = data.copy()
        data.setflags(write=False)
    return data


class ProductCache:
    """Two-tier (RAM over disk) content-addressed product cache.

    ``ram_bytes`` bounds the RAM tier (0 disables it); ``root=None``
    disables the disk tier (RAM-only cache).  ``disk_bytes`` optionally
    bounds the disk tier — oldest completed entries are evicted when a
    publish would exceed it.  All methods are thread-safe.
    """

    def __init__(
        self,
        root: Optional[str] = None,
        *,
        ram_bytes: int = 1 << 30,
        disk_bytes: Optional[int] = None,
        cold_dir: Optional[str] = None,
        timeline: Optional[Timeline] = None,
    ):
        self.root = root
        self.ram_bytes = max(0, int(ram_bytes))
        self.disk_bytes = disk_bytes
        # Cold tier (ISSUE 19): requires a hot disk tier to promote
        # into — a RAM-only cache with a cold_dir would demote nothing
        # and have nowhere to promote, so it is simply ignored.
        self.cold_dir = cold_dir if root is not None else None
        self.timeline = timeline if timeline is not None else Timeline()
        self._lock = threading.Lock()
        # fp -> (header, read-only data, nbytes); insertion order = LRU.
        self._ram: "OrderedDict[str, Tuple[Dict, np.ndarray, int]]" = (
            OrderedDict()
        )
        self._ram_used = 0
        # Encoded-wire-body tier (ISSUE 16): fp -> (frame bytes,
        # nbytes), LRU, sharing ram_bytes with the product entries but
        # evicted first — a wire body is re-derivable from its product.
        self._wire: "OrderedDict[str, Tuple[bytes, int]]" = OrderedDict()
        self._wire_used = 0
        # Per-fingerprint hit totals (bounded: RAM/disk hits only, LRU
        # pruned alongside the RAM tier) — the fleet plane's hotness
        # signal (ISSUE 14): `hot()` feeds cache-warm replication and
        # the drain-time hot-entry hints.
        self._hits_by_fp: "OrderedDict[str, int]" = OrderedDict()
        self.counts: Dict[str, int] = {
            "hit.ram": 0, "hit.disk": 0, "hit.wire": 0, "hit.cold": 0,
            "miss": 0, "evict.ram": 0, "evict.disk": 0,
            "evict.corrupt": 0, "evict.wire": 0, "demote.cold": 0,
            "promote.cold": 0, "derive": 0, "publish": 0,
            "publish.error": 0,
        }
        if root is not None:
            os.makedirs(root, exist_ok=True)
            # Integrity plane (ISSUE 13): the disk tier's quarantine dir
            # joins the /healthz watch set — a serve process whose cache
            # grew a quarantine reports degraded until triaged.
            from blit import integrity

            integrity.watch_quarantine(
                os.path.join(root, integrity.QUARANTINE_DIR))
        if self.cold_dir is not None:
            os.makedirs(self.cold_dir, exist_ok=True)

    # -- paths -------------------------------------------------------------
    def data_path(self, fp: str) -> str:
        return os.path.join(self.root, f"{fp}.h5")

    def meta_path(self, fp: str) -> str:
        return os.path.join(self.root, f"{fp}.json")

    def wire_path(self, fp: str) -> str:
        return os.path.join(self.root, f"{fp}.wire")

    def cold_data_path(self, fp: str) -> str:
        return os.path.join(self.cold_dir, fp[:2], f"{fp}.h5")

    def cold_meta_path(self, fp: str) -> str:
        return os.path.join(self.cold_dir, fp[:2], f"{fp}.json")

    # -- counters ----------------------------------------------------------
    def _count(self, name: str, n: int = 1) -> None:
        with self._lock:
            self.counts[name] = self.counts.get(name, 0) + n
        self.timeline.count(f"cache.{name}", n)

    def stats(self) -> Dict[str, int]:
        with self._lock:
            out = dict(self.counts)
            out["ram_entries"] = len(self._ram)
            out["ram_bytes_used"] = self._ram_used
            out["wire_entries"] = len(self._wire)
            out["wire_bytes_used"] = self._wire_used
        return out

    @property
    def hit_rate(self) -> float:
        s = self.stats()
        served = s["hit.ram"] + s["hit.disk"] + s["hit.cold"]
        total = served + s["miss"]
        return served / total if total else 0.0

    def note_derive(self) -> None:
        """One miss re-derived through the reduce path — the serve
        layer reports it so the per-tier story on /metrics covers all
        of {ram, wire, disk, cold, derive} (ISSUE 19)."""
        self._count("derive")

    # -- RAM tier ----------------------------------------------------------
    def _evict_wire_locked(self, need: int) -> None:
        """Drop LRU wire bodies until ``need`` more bytes fit the
        shared budget (wire bodies go first: re-derivable)."""
        while (self._ram_used + self._wire_used + need > self.ram_bytes
               and self._wire):
            _, (_, b) = self._wire.popitem(last=False)
            self._wire_used -= b
            self.counts["evict.wire"] += 1
            self.timeline.count("cache.evict.wire")

    def _ram_put_locked(self, fp: str, header: Dict,
                        data: np.ndarray) -> None:
        nbytes = data.nbytes
        if nbytes > self.ram_bytes:
            return  # larger than the whole budget: disk-only entry
        old = self._ram.pop(fp, None)
        if old is not None:
            self._ram_used -= old[2]
        self._evict_wire_locked(nbytes)
        while (self._ram_used + self._wire_used + nbytes > self.ram_bytes
               and self._ram):
            _, (_, _, b) = self._ram.popitem(last=False)
            self._ram_used -= b
            self.counts["evict.ram"] += 1
            self.timeline.count("cache.evict.ram")
        self._ram[fp] = (header, data, nbytes)
        self._ram_used += nbytes

    def _wire_put_locked(self, fp: str, body: bytes) -> None:
        """RAM leg of the wire tier: evicts only OTHER wire bodies —
        never a product entry — and declines when products already
        fill the budget (the body stays derivable)."""
        nbytes = len(body)
        old = self._wire.pop(fp, None)
        if old is not None:
            self._wire_used -= old[1]
        self._evict_wire_locked(nbytes)
        if self._ram_used + self._wire_used + nbytes <= self.ram_bytes:
            self._wire[fp] = (bytes(body), nbytes)
            self._wire_used += nbytes

    # -- disk tier ---------------------------------------------------------
    def _disk_publish(self, fp: str, header: Dict, data: np.ndarray,
                      recipe: Optional[Dict] = None) -> None:
        """Atomic publish: data file first, sidecar last, both via
        write-temp-``os.replace`` — the sidecar's existence marks a
        complete entry.  Raises on failure (the caller downgrades to a
        RAM/serve-only result and counts it).

        The meta sidecar carries the entry's CONTENT digest (a CRC over
        the published file's bytes, ISSUE 13) — loads and the background
        scrubber verify it, turning the structural resume probe into
        content verification — plus the optional ``recipe`` (the
        serve request's knob surface) so ``blit fsck --repair`` can
        re-derive a quarantined entry: the fingerprint is already a
        content-addressed recipe key, the recipe makes it executable."""
        from blit import integrity
        from blit.io import write_fbh5

        faults.fire("cache.publish", key=fp)
        self._disk_evict_for(data.nbytes)
        suffix = f".tmp.{os.getpid()}.{threading.get_ident()}"
        dtmp = self.data_path(fp) + suffix
        mtmp = self.meta_path(fp) + suffix
        try:
            write_fbh5(dtmp, header, np.ascontiguousarray(data))
            file_crc = integrity.crc32_file(dtmp)
            file_bytes = os.path.getsize(dtmp)
            os.replace(dtmp, self.data_path(fp))
            meta = {"fingerprint": fp, "nsamps": int(data.shape[0]),
                    "nifs": int(data.shape[1]),
                    "nchans": int(data.shape[2]),
                    "nbytes": int(data.nbytes),
                    "crc32": integrity.hex_crc(file_crc),
                    "file_bytes": int(file_bytes),
                    "header": _jsonable(header)}
            if recipe is not None:
                meta["recipe"] = recipe
            with open(mtmp, "w") as f:
                json.dump(meta, f)
                f.flush()
                os.fsync(f.fileno())
            os.replace(mtmp, self.meta_path(fp))
        finally:
            for t in (dtmp, mtmp):
                try:
                    os.unlink(t)
                except OSError:
                    pass

    def _disk_evict(self, fp: str, reason: str) -> None:
        for p in (self.meta_path(fp), self.data_path(fp),
                  self.wire_path(fp)):
            try:
                os.unlink(p)
            except OSError:
                pass
        self._count(f"evict.{reason}")

    def _disk_evict_for(self, incoming: int) -> None:
        """Make room for ``incoming`` bytes under ``disk_bytes`` (oldest
        completed entries first; no-op without a budget).  Also sweeps
        sidecar-less ``.h5`` orphans (a crash between the data and
        sidecar renames) old enough to not be a publish in progress —
        they are invisible to :meth:`index` and would otherwise leak
        outside the budget forever."""
        if self.disk_bytes is None:
            return
        complete = set(self.index())
        now_ns = time.time_ns()
        entries = []
        total = 0
        try:
            names = os.listdir(self.root)
        except OSError:
            names = []
        for n in names:
            if not n.endswith(".h5"):
                continue
            fp = n[:-3]
            try:
                st = os.stat(os.path.join(self.root, n))
            except OSError:
                continue
            if fp not in complete:
                if now_ns - st.st_mtime_ns > 60 * 10**9:
                    self._disk_evict(fp, "disk")  # crash-orphaned data
                continue
            size = st.st_size
            try:
                # The entry's wire body is budgeted (and evicted) with
                # its product file.
                size += os.path.getsize(self.wire_path(fp))
            except OSError:
                pass
            entries.append((st.st_mtime_ns, fp, size))
            total += size
        entries.sort()
        while entries and total + incoming > self.disk_bytes:
            _, fp, size = entries.pop(0)
            # With a cold tier, a capacity eviction DEMOTES instead of
            # deleting (ISSUE 19): the entry's bytes move to the
            # object-store layout, promotable on the next hit.
            if self.cold_dir is not None and self._demote(fp):
                self._count("demote.cold")
            else:
                self._disk_evict(fp, "disk")
            total -= size

    def _disk_load(self, fp: str) -> Optional[Tuple[Dict, np.ndarray]]:
        """Load a completed disk entry, probing it for corruption first —
        an entry that no longer reads as the product its sidecar claims is
        evicted (count ``evict.corrupt``) and reported as a miss."""
        from blit.io import read_fbh5_data
        from blit.io.fbh5 import resume_target_ok

        mpath = self.meta_path(fp)
        if not os.path.exists(mpath):
            return None
        try:
            with open(mpath) as f:
                meta = json.load(f)
        except (OSError, ValueError):
            self._disk_evict(fp, "corrupt")
            return None
        nsamps = int(meta.get("nsamps", -1))
        if nsamps < 0 or not resume_target_ok(
            self.data_path(fp), int(meta["nifs"]), int(meta["nchans"]),
            nsamps,
        ):
            log.warning("cache entry %s is unreadable; evicting", fp[:16])
            self._disk_evict(fp, "corrupt")
            return None
        # Content verification (ISSUE 13): the structural probe above
        # cannot see a flipped byte inside a structurally valid file —
        # the published content digest can.  BLIT_VERIFY_CACHE=0 is the
        # escape hatch; entries published before the digest existed
        # keep the structural-probe behavior.
        from blit import integrity

        want = integrity.parse_crc(meta.get("crc32"))
        if want is not None and integrity.cache_verify_enabled():
            t0 = time.perf_counter()
            got = integrity.crc32_file(self.data_path(fp))
            integrity.observe_verify(time.perf_counter() - t0,
                                     self.timeline)
            if got != want:
                integrity.incr("integrity.cache.corrupt")
                log.warning(
                    "cache entry %s fails its content digest (%s != "
                    "%s); evicting", fp[:16], integrity.hex_crc(got),
                    meta["crc32"])
                self._disk_evict(fp, "corrupt")
                return None
        try:
            data = read_fbh5_data(self.data_path(fp))
        except Exception:  # noqa: BLE001 — corrupt past the probe: evict
            self._disk_evict(fp, "corrupt")
            return None
        return meta["header"], _frozen(data)

    # -- cold tier (ISSUE 19 tentpole #2) ----------------------------------
    def _demote(self, fp: str) -> bool:
        """Move a completed hot-tier entry into the cold layout (data
        file first, sidecar last — the publish ordering rule, so the
        cold sidecar's existence marks a complete cold entry).  The
        derived ``.wire`` body is dropped, not demoted: it re-derives
        from the product in one encode.  Returns False (caller falls
        back to a plain eviction) when the move fails midway."""
        mpath, dpath = self.meta_path(fp), self.data_path(fp)
        if not (os.path.exists(mpath) and os.path.exists(dpath)):
            return False
        try:
            os.makedirs(os.path.join(self.cold_dir, fp[:2]),
                        exist_ok=True)
            shutil.move(dpath, self.cold_data_path(fp))
            shutil.move(mpath, self.cold_meta_path(fp))
        except OSError as e:
            log.warning("demote of %s to the cold tier failed: %s",
                        fp[:16], e)
            return False
        try:
            os.unlink(self.wire_path(fp))
        except OSError:
            pass
        self.timeline.count("cache.demote.cold")
        return True

    def _cold_evict(self, fp: str) -> None:
        for p in (self.cold_meta_path(fp), self.cold_data_path(fp)):
            try:
                os.unlink(p)
            except OSError:
                pass
        self._count("evict.corrupt")

    def _cold_load(self, fp: str) -> Optional[Tuple[Dict, np.ndarray]]:
        """Cold hit: CRC-verify the cold entry against its manifest
        sidecar (the promotion gate, PR-12 rules — a cold entry that
        fails its digest is EVICTED and reported as a miss, never
        promoted), then PROMOTE it into the hot disk tier byte-for-byte
        (files copied, sidecar last) and load through the normal hot
        path."""
        from blit import integrity
        from blit.io import read_fbh5_data

        mpath = self.cold_meta_path(fp)
        dpath = self.cold_data_path(fp)
        if not os.path.exists(mpath):
            return None
        try:
            with open(mpath) as f:
                meta = json.load(f)
            want = integrity.parse_crc(meta.get("crc32"))
        except (OSError, ValueError):
            self._cold_evict(fp)
            return None
        if want is not None and integrity.cache_verify_enabled():
            t0 = time.perf_counter()
            try:
                got = integrity.crc32_file(dpath)
            except OSError:
                got = None
            integrity.observe_verify(time.perf_counter() - t0,
                                     self.timeline)
            if got != want:
                integrity.incr("integrity.cache.corrupt")
                log.warning("cold entry %s fails its content digest; "
                            "evicting", fp[:16])
                self._cold_evict(fp)
                return None
        # Promote: data before sidecar, both via temp + os.replace —
        # the hot tier sees a whole entry or none, and the bytes are
        # the EXACT bytes the cold manifest just verified.
        suffix = f".tmp.{os.getpid()}.{threading.get_ident()}"
        dtmp = self.data_path(fp) + suffix
        mtmp = self.meta_path(fp) + suffix
        try:
            self._disk_evict_for(
                int(meta.get("file_bytes") or 0)
                or (os.path.getsize(dpath) if os.path.exists(dpath)
                    else 0))
            shutil.copyfile(dpath, dtmp)
            os.replace(dtmp, self.data_path(fp))
            shutil.copyfile(mpath, mtmp)
            os.replace(mtmp, self.meta_path(fp))
        except OSError as e:
            log.warning("promotion of cold entry %s failed: %s",
                        fp[:16], e)
            for t in (dtmp, mtmp):
                try:
                    os.unlink(t)
                except OSError:
                    pass
            # Serve from the cold files directly this once.
            try:
                data = read_fbh5_data(dpath)
            except Exception:  # noqa: BLE001 — rot past the CRC gate
                self._cold_evict(fp)
                return None
            return meta["header"], _frozen(data)
        self._count("promote.cold")
        self._cold_evict_entry_files_after_promote(fp)
        try:
            data = read_fbh5_data(self.data_path(fp))
        except Exception:  # noqa: BLE001 — corrupt past the probe: evict
            self._disk_evict(fp, "corrupt")
            return None
        return meta["header"], _frozen(data)

    def _cold_evict_entry_files_after_promote(self, fp: str) -> None:
        """After a verified promotion the hot tier owns the entry; the
        cold copy is removed so one fingerprint lives in exactly one
        durable tier (a later demotion re-creates it)."""
        for p in (self.cold_meta_path(fp), self.cold_data_path(fp)):
            try:
                os.unlink(p)
            except OSError:
                pass

    def cold_index(self) -> list:
        """Fingerprints of the completed COLD entries (sidecar
        present), sorted — the fsck/drill view of the cold tier."""
        if self.cold_dir is None:
            return []
        out = []
        try:
            shards = sorted(os.listdir(self.cold_dir))
        except OSError:
            return []
        for shard in shards:
            sub = os.path.join(self.cold_dir, shard)
            if not os.path.isdir(sub):
                continue
            try:
                names = os.listdir(sub)
            except OSError:
                continue
            out.extend(n[:-5] for n in names if n.endswith(".json"))
        return sorted(out)

    # -- encoded wire bodies (ISSUE 16 tentpole #3) ------------------------
    def _wire_publish(self, fp: str, body: bytes) -> None:
        """Atomic ``<fp>.wire`` spill: frame bytes + big-endian CRC32
        footer, write-temp-``os.replace`` — a disk wire hit streams
        these bytes back without materializing the array."""
        suffix = f".tmp.{os.getpid()}.{threading.get_ident()}"
        tmp = self.wire_path(fp) + suffix
        crc = zlib.crc32(body) & 0xFFFFFFFF
        try:
            with open(tmp, "wb") as f:
                f.write(body)
                f.write(crc.to_bytes(4, "big"))
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, self.wire_path(fp))
        finally:
            try:
                os.unlink(tmp)
            except OSError:
                pass

    def _wire_load(self, fp: str) -> Optional[bytes]:
        """Read + CRC-verify a ``.wire`` file (PR 12 discipline: the
        footer guards every load unless ``BLIT_VERIFY_CACHE=0``); a
        failing body is unlinked and counted ``evict.corrupt`` —
        the PRODUCT entry, verified separately, stays servable."""
        from blit import integrity

        path = self.wire_path(fp)
        try:
            with open(path, "rb") as f:
                blob = f.read()
        except OSError:
            return None
        ok = len(blob) >= 4
        if ok and integrity.cache_verify_enabled():
            t0 = time.perf_counter()
            ok = ((zlib.crc32(blob[:-4]) & 0xFFFFFFFF)
                  == int.from_bytes(blob[-4:], "big"))
            integrity.observe_verify(time.perf_counter() - t0,
                                     self.timeline)
        if not ok:
            integrity.incr("integrity.cache.corrupt")
            log.warning("wire body %s fails its CRC footer; evicting",
                        fp[:16])
            try:
                os.unlink(path)
            except OSError:
                pass
            self._count("evict.corrupt")
            return None
        return blob[:-4]

    def get_wire(self, fp: str) -> Optional[Tuple[bytes, str]]:
        """``(encoded wire body, tier)`` for an entry whose framed form
        is retained (``tier`` in ``("ram", "disk")``), or ``None`` —
        which is NOT counted as a miss: the caller falls back to
        :meth:`get` (which counts), so per-tier accounting stays
        single-entry.  Hits count ``hit.ram``/``hit.disk`` like any
        other hit, plus ``hit.wire`` naming the fast path taken."""
        with self._lock:
            hit = self._wire.get(fp)
            if hit is not None:
                self._wire.move_to_end(fp)
                self.counts["hit.ram"] += 1
                self.counts["hit.wire"] += 1
                self._note_hit_locked(fp)
                self.timeline.count("cache.hit.ram")
                self.timeline.count("cache.hit.wire")
                return hit[0], "ram"
        if self.root is None:
            return None
        body = self._wire_load(fp)
        if body is None:
            return None
        with self._lock:
            self._wire_put_locked(fp, body)
            self.counts["hit.disk"] += 1
            self.counts["hit.wire"] += 1
            self._note_hit_locked(fp)
        self.timeline.count("cache.hit.disk")
        self.timeline.count("cache.hit.wire")
        return body, "disk"

    def put_wire(self, fp: str, body: bytes) -> None:
        """Retain the already-encoded wire body of a completed entry:
        the next binary-wire hit is one memoryview write — no
        re-encode, no ndarray copy.  RAM (shared budget, wire-first
        eviction, never displacing a product) then disk spill; a
        failed spill is logged and dropped — the body is re-derivable
        from its product, so losing it costs one future encode."""
        with self._lock:
            self._wire_put_locked(fp, body)
        if self.root is not None:
            try:
                self._wire_publish(fp, body)
            except OSError as e:
                log.warning("wire spill of %s failed: %s", fp[:16], e)

    # -- public surface ----------------------------------------------------
    def get(self, fp: str) -> Optional[Tuple[Dict, np.ndarray, str]]:
        """``(header, read-only data, tier)`` for a completed entry
        (``tier`` in ``("ram", "disk", "cold")``; disk hits are promoted
        to RAM, cold hits are CRC-verified and promoted to disk+RAM),
        or ``None`` on a miss."""
        with self._lock:
            hit = self._ram.get(fp)
            if hit is not None:
                self._ram.move_to_end(fp)
                self.counts["hit.ram"] += 1
                self._note_hit_locked(fp)
                self.timeline.count("cache.hit.ram")
                # dict() copy out: the array is frozen, but a caller
                # mutating a by-reference header would corrupt the entry
                # for every later hitter.
                return dict(hit[0]), hit[1], "ram"
        if self.root is not None:
            loaded = self._disk_load(fp)
            if loaded is not None:
                header, data = loaded
                with self._lock:
                    self._ram_put_locked(fp, header, data)
                    self.counts["hit.disk"] += 1
                    self._note_hit_locked(fp)
                self.timeline.count("cache.hit.disk")
                return dict(header), data, "disk"
        if self.cold_dir is not None:
            loaded = self._cold_load(fp)
            if loaded is not None:
                header, data = loaded
                with self._lock:
                    self._ram_put_locked(fp, header, data)
                    self.counts["hit.cold"] += 1
                    self._note_hit_locked(fp)
                self.timeline.count("cache.hit.cold")
                return dict(header), data, "cold"
        self._count("miss")
        return None

    def put(self, fp: str, header: Dict, data: np.ndarray,
            *, recipe: Optional[Dict] = None) -> np.ndarray:
        """Publish a finished product under ``fp`` (RAM, then disk spill).
        A disk-publish failure (including an injected ``cache.publish``
        fault) downgrades to a RAM-only entry — the result in hand is
        still correct and MUST still be served (count
        ``publish.error``).  Returns the read-only array the cache will
        serve, so the publisher and later hitters share bytes.
        ``recipe`` (the serve request's knob surface, ISSUE 13) rides
        the meta sidecar so ``blit fsck --repair`` can re-derive the
        entry after a quarantine."""
        data = _frozen(data)
        header = dict(header)
        with self._lock:
            self._ram_put_locked(fp, header, data)
            self.counts["publish"] += 1
        if self.root is not None:
            try:
                self._disk_publish(fp, header, data, recipe=recipe)
            except Exception as e:  # noqa: BLE001 — serve-path must survive
                log.warning("disk publish of %s failed: %s", fp[:16], e)
                self._count("publish.error")
                if not os.path.exists(self.meta_path(fp)):
                    # A data file that landed without its sidecar (the
                    # failure hit between the two renames) is an orphan
                    # no index/eviction pass would ever reclaim.
                    try:
                        os.unlink(self.data_path(fp))
                    except OSError:
                        pass
        return data

    def verify_entry(self, fp: str, quarantine: bool = False
                     ) -> Optional[bool]:
        """Content-verify one completed disk entry (the scrubber's and
        ``blit fsck``'s unit of work, ISSUE 13).  Returns None when the
        entry does not exist, True when it verifies, False when it does
        not — in which case it is QUARANTINED (moved into
        ``<root>/.quarantine/``, inspectable, no longer servable) when
        asked, else evicted; either way counted ``evict.corrupt``."""
        from blit import integrity

        if self.root is None:
            return None
        mpath = self.meta_path(fp)
        if not os.path.exists(mpath):
            return None
        ok = True
        try:
            with open(mpath) as f:
                meta = json.load(f)
            want = integrity.parse_crc(meta.get("crc32"))
            if want is not None:
                ok = integrity.crc32_file(self.data_path(fp)) == want
            else:
                from blit.io.fbh5 import resume_target_ok

                ok = resume_target_ok(
                    self.data_path(fp), int(meta["nifs"]),
                    int(meta["nchans"]), int(meta["nsamps"]))
        except (OSError, ValueError, KeyError, TypeError):
            ok = False  # torn meta / missing data: fail closed
        if ok:
            # The derived wire body is scrubbed alongside its product:
            # a failing footer costs ONLY the encoded copy (unlinked,
            # counted) — the verified product entry stays servable.
            if os.path.exists(self.wire_path(fp)):
                self._wire_load(fp)
            return True
        integrity.incr("integrity.cache.corrupt")
        log.warning("cache entry %s failed verification; %s", fp[:16],
                    "quarantining" if quarantine else "evicting")
        if quarantine:
            integrity.quarantine_move(
                [self.data_path(fp), mpath], self.root)
            try:
                os.unlink(self.wire_path(fp))
            except OSError:
                pass
            with self._lock:
                old = self._ram.pop(fp, None)
                if old is not None:
                    self._ram_used -= old[2]
                old_wire = self._wire.pop(fp, None)
                if old_wire is not None:
                    self._wire_used -= old_wire[1]
            self._count("evict.corrupt")
        else:
            self._disk_evict(fp, "corrupt")
        return False

    # Hotness-tracking bound: enough for any realistic hot set, small
    # enough that the tracker can never become the memory story.
    _HOT_TRACK_MAX = 4096

    def _note_hit_locked(self, fp: str) -> None:
        self._hits_by_fp[fp] = self._hits_by_fp.get(fp, 0) + 1
        self._hits_by_fp.move_to_end(fp)
        while len(self._hits_by_fp) > self._HOT_TRACK_MAX:
            self._hits_by_fp.popitem(last=False)

    def warm_range(self, in_range=None, n: int = 16) -> list:
        """The ``n`` hottest fingerprints as ``(fp, hits)`` pairs,
        hit-count descending (recency breaks ties), restricted to the
        fingerprints ``in_range`` accepts (a predicate; None = all).
        The range-scoped form serves elastic warm handoff (ISSUE 17):
        a resize moves exactly one peer's key range, so only entries in
        that range are worth streaming to the joiner."""
        with self._lock:
            items = list(self._hits_by_fp.items())
        items.reverse()  # most-recent first → stable tie-break
        items.sort(key=lambda kv: kv[1], reverse=True)
        if in_range is not None:
            items = [kv for kv in items if in_range(kv[0])]
        return items[:max(0, int(n))]

    def hot(self, n: int = 16) -> list:
        """The ``n`` hottest fingerprints as ``(fp, hits)`` pairs —
        the full-keyspace :meth:`warm_range` view (the fleet plane's
        cache-warm / drain-hint source, ISSUE 14)."""
        return self.warm_range(None, n)

    def contains(self, fp: str) -> bool:
        with self._lock:
            if fp in self._ram:
                return True
        if self.root is not None and os.path.exists(self.meta_path(fp)):
            return True
        return (self.cold_dir is not None
                and os.path.exists(self.cold_meta_path(fp)))

    def index(self) -> list:
        """Fingerprints of the COMPLETED disk entries (sidecar present)."""
        if self.root is None:
            return []
        try:
            names = os.listdir(self.root)
        except OSError:
            return []
        return sorted(n[:-5] for n in names if n.endswith(".json"))

    def clear(self) -> None:
        with self._lock:
            self._ram.clear()
            self._ram_used = 0
            self._wire.clear()
            self._wire_used = 0
        for fp in self.index():
            self._disk_evict(fp, "disk")
        # A wire body can outlive its product entry (RAM-only product,
        # spilled frame) — sweep stray .wire files too.
        if self.root:
            try:
                names = os.listdir(self.root)
            except OSError:
                names = []
            for n in names:
                if n.endswith(".wire"):
                    try:
                        os.unlink(os.path.join(self.root, n))
                    except OSError:
                        pass


def _jsonable(header: Dict) -> Dict:
    """The JSON-safe view of a product header (numpy scalars → Python)."""
    out = {}
    for k, v in header.items():
        if isinstance(v, np.generic):
            v = v.item()
        out[k] = v
    return out
