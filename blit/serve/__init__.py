"""blit.serve — the product service layer (ISSUE 3).

The multi-tenant serving stack over the reduction machinery:

- :mod:`blit.serve.cache` — two-tier (RAM LRU over disk FBH5)
  content-addressed product cache keyed by reduction fingerprint;
- :mod:`blit.serve.scheduler` — priority scheduler with admission control
  (bounded queues, :class:`Overloaded` rejection, fair share across
  clients, health-aware concurrency budget);
- :mod:`blit.serve.service` — :class:`ProductService`, the front door:
  ``submit() -> Ticket`` / ``result()`` / ``get()``, single-flight
  request coalescing, cache-first serving.

The FLEET plane (ISSUE 14) scales the same stack across hosts:

- :mod:`blit.serve.ring` — :class:`HashRing`, consistent-hash routing
  of fingerprints to owner+replica peer sets;
- :mod:`blit.serve.http` — the stdlib-HTTP wire: :class:`PeerServer`
  (one ProductService served over ``/product`` with lease heartbeats
  and the monitor plane's ``/metrics``–``/healthz``) and
  :class:`FrontDoorServer`;
- :mod:`blit.serve.fleet` — :class:`FleetFrontDoor`: ring routing,
  lease-driven peer ejection/rejoin, per-peer breakers, hedged reads
  off the live p99, deadline propagation, cache-warm replication and
  graceful drain.

The HOT-PATH data plane (ISSUE 16) makes the fleet wire fast:
:class:`ConnectionPool` keep-alive sockets on every hop, the
``application/x-blit-product`` binary frame
(:class:`~blit.serve.http.WireError` guards decode) negotiated by
``Accept``, and the cache's encoded-wire-body tier so a hot hit never
re-encodes.

The ELASTIC plane (ISSUE 17) closes the burn-rate→membership loop:
:class:`FleetController` admits lease-fresh standbys after a
range-scoped warm handoff when the SLO pages, and drains/retires the
coldest peer after sustained idle — hysteresis-gated so membership
never flaps.
"""

from blit.serve.cache import (
    ProductCache,
    fingerprint_for,
    reduction_fingerprint,
)
from blit.serve.elastic import FleetController
from blit.serve.fleet import FleetError, FleetFrontDoor
from blit.serve.http import (
    ConnectionPool,
    FrontDoorServer,
    PeerServer,
    WireError,
)
from blit.serve.ring import HashRing
from blit.serve.scheduler import (
    Cancelled,
    DeadlineExpired,
    Job,
    Overloaded,
    Scheduler,
)
from blit.serve.service import ProductRequest, ProductService, Ticket

__all__ = [
    "Cancelled",
    "ConnectionPool",
    "DeadlineExpired",
    "FleetController",
    "FleetError",
    "FleetFrontDoor",
    "FrontDoorServer",
    "HashRing",
    "Job",
    "Overloaded",
    "PeerServer",
    "ProductCache",
    "ProductRequest",
    "ProductService",
    "Scheduler",
    "Ticket",
    "WireError",
    "fingerprint_for",
    "reduction_fingerprint",
]
