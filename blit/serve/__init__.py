"""blit.serve — the product service layer (ISSUE 3).

The multi-tenant serving stack over the reduction machinery:

- :mod:`blit.serve.cache` — two-tier (RAM LRU over disk FBH5)
  content-addressed product cache keyed by reduction fingerprint;
- :mod:`blit.serve.scheduler` — priority scheduler with admission control
  (bounded queues, :class:`Overloaded` rejection, fair share across
  clients, health-aware concurrency budget);
- :mod:`blit.serve.service` — :class:`ProductService`, the front door:
  ``submit() -> Ticket`` / ``result()`` / ``get()``, single-flight
  request coalescing, cache-first serving.
"""

from blit.serve.cache import (
    ProductCache,
    fingerprint_for,
    reduction_fingerprint,
)
from blit.serve.scheduler import Cancelled, Job, Overloaded, Scheduler
from blit.serve.service import ProductRequest, ProductService, Ticket

__all__ = [
    "Cancelled",
    "Job",
    "Overloaded",
    "ProductCache",
    "ProductRequest",
    "ProductService",
    "Scheduler",
    "Ticket",
    "fingerprint_for",
    "reduction_fingerprint",
]
