"""Priority job scheduler with admission control (ISSUE 3 tentpole).

The queueing discipline between many callers and the bounded reduction
machinery.  Design points, in the order a request meets them:

- **Admission control** — each priority level has a BOUNDED queue
  (``SiteConfig.serve_queue_depth``).  A submission that would overflow
  it, or whose deadline provably cannot be met given the current backlog,
  is rejected immediately with :class:`Overloaded` carrying a
  ``retry_after_s`` hint — overload must surface as a fast, explicit
  signal, never as unbounded queue growth or a silent hang (the
  serving-stack shape the SNIPPETS dispatch-overhead benchmarks argue
  for: per-request cost stays flat under load).
- **Fair share** — within a priority, queues are PER CLIENT and service
  is round-robin across clients, so one caller fanning out thousands of
  requests cannot starve everyone else; across priorities, lower numbers
  always dispatch first.
- **Concurrency budget** — at most ``budget`` jobs run at once.  With a
  :class:`~blit.parallel.pool.WorkerPool` attached the budget shrinks
  proportionally with degraded hosts (tripped circuit breakers,
  ``pool.health()``): a half-degraded cluster admits half the work
  instead of piling the same load onto the surviving hosts.
- **Observability** — queue-depth and per-job wait gauges land on the
  :class:`~blit.observability.Timeline` (``sched.queue_depth`` /
  ``sched.wait_s``), the wait distribution lives in a bounded
  :class:`~blit.observability.HistogramStats` (p50/p99 at fixed memory
  for the life of the scheduler — ISSUE 5 satellite), and the
  ``sched.dispatch`` fault-injection point covers the dispatch path so
  drills (blit/faults.py) reach the serving layer.

Jobs run on daemon threads (one per running job, capped by the budget —
the work itself releases the GIL in NumPy/HDF5/XLA, same reasoning as the
pool's thread backend).  ``clock`` is injectable so tests steer time.
"""

from __future__ import annotations

import logging
import random
import threading
import time
from collections import deque
from typing import Callable, Deque, Dict, Optional

from blit import faults
from blit.observability import HistogramStats, Timeline

log = logging.getLogger("blit.serve.sched")


class Overloaded(RuntimeError):
    """Admission refused: queue full or deadline unmeetable.  Callers
    should back off at least ``retry_after_s`` before resubmitting.

    ``retry_after_s`` carries seeded JITTER (ISSUE 14 satellite): the
    raw estimate is deterministic, so a burst of simultaneously rejected
    clients obeying it verbatim would all come back in the same instant
    — the thundering herd the rejection was shedding.  The scheduler
    spreads them with the :class:`blit.faults.RetryPolicy` jitter
    discipline (uniform in ``est * (1 ± jitter)``, a pure function of
    ``(seed, rejection index)`` when seeded), and the HTTP front door
    honors the jittered value as the 503 ``Retry-After`` header."""

    def __init__(self, message: str, retry_after_s: float = 1.0):
        super().__init__(message)
        self.retry_after_s = retry_after_s


class DeadlineExpired(Overloaded):
    """The job's deadline burned before it could run (rejected at
    admission, or dropped at dispatch time after queueing past it) —
    the work was never computed.  An :class:`Overloaded` subclass so
    existing back-off handling applies."""


class Cancelled(RuntimeError):
    """The job was cancelled while still queued."""


def classify_failure(e: BaseException):
    """The access-record ``(status, code)`` of a serve-path failure —
    ONE mapping shared by the fleet door, the peer HTTP handler and
    ``ProductService.get`` (ISSUE 15), so one failure shape never
    yields three different record shapes in one spool.  Success is the
    caller's ``("ok", 200)``; order matters (DeadlineExpired ⊂
    Overloaded).  A bare ``TimeoutError`` (the caller's wait budget
    burned with no declared deadline) records as ``timeout``/504 — a
    deadline-class outcome for the requester."""
    if isinstance(e, DeadlineExpired):
        return "deadline", 504
    if isinstance(e, Overloaded):
        return "overloaded", 503
    if isinstance(e, TimeoutError):
        return "timeout", 504
    # Archive catalog misses (ISSUE 19) are the caller naming a
    # session/scan the tree does not hold — not-found, not a fault.
    if type(e).__name__ == "CatalogMiss":
        return "notfound", 404
    return "error", 500


class Job:
    """One scheduled unit of work.  ``wait()``/``result()`` block on
    completion; queue/run timings hang off the instance for reporting."""

    __slots__ = ("fn", "priority", "client", "deadline_s", "submitted_at",
                 "started_at", "finished_at", "state", "_result", "_exc",
                 "_done", "held", "on_drop")

    def __init__(self, fn: Callable[[], object], priority: int, client: str,
                 deadline_s: Optional[float], now: float,
                 held: bool = False,
                 on_drop: Optional[Callable[[BaseException], None]] = None):
        self.fn = fn
        # Called (on its own thread, like fn would have been) when the
        # scheduler DROPS the job without running it — dispatch-time
        # deadline expiry.  The service layer uses it to fail the
        # single-flight group, so waiters and later coalescers are not
        # left hanging on a job whose fn never ran (ISSUE 14 review).
        self.on_drop = on_drop
        self.priority = priority
        self.client = client
        self.deadline_s = deadline_s
        # Session-length capacity hold (ISSUE 12 satellite): a live job
        # that runs for the recording's duration, not a bounded
        # reduction — it consumes a concurrency slot but is EXCLUDED
        # from the EWMA service model and the deadline estimator's
        # work-ahead count (an unbounded job would poison both).
        self.held = held
        self.submitted_at = now
        self.started_at: Optional[float] = None
        self.finished_at: Optional[float] = None
        self.state = "queued"  # queued | running | done | cancelled
        self._result: object = None
        self._exc: Optional[BaseException] = None
        self._done = threading.Event()

    @property
    def wait_s(self) -> Optional[float]:
        """Seconds spent queued (None until dispatch)."""
        if self.started_at is None:
            return None
        return self.started_at - self.submitted_at

    def wait(self, timeout: Optional[float] = None) -> bool:
        return self._done.wait(timeout)

    def result(self, timeout: Optional[float] = None) -> object:
        if not self._done.wait(timeout):
            raise TimeoutError(
                f"job for client {self.client!r} not done within {timeout}s"
            )
        if self._exc is not None:
            raise self._exc
        return self._result


class Scheduler:
    """Bounded, fair-share, health-aware job scheduler (module docstring).

    ``max_concurrency`` is the base budget; ``pool`` (optional) shrinks it
    with degraded hosts; ``queue_depth`` bounds EACH priority's queue.
    """

    def __init__(
        self,
        *,
        max_concurrency: int = 4,
        queue_depth: int = 64,
        pool=None,
        timeline: Optional[Timeline] = None,
        clock: Callable[[], float] = time.monotonic,
        wait_est_floor: int = 32,
        retry_jitter: float = 0.5,
        retry_seed: Optional[int] = None,
    ):
        self.max_concurrency = max(1, int(max_concurrency))
        self.queue_depth = max(1, int(queue_depth))
        # Thundering-herd spread on rejection (ISSUE 14 satellite): the
        # RetryPolicy jitter discipline applied to retry_after_s.  With
        # retry_seed set, rejection k's jitter is a pure function of
        # (seed, k) — deterministic across runs, different across
        # rejections, so a drill replays the exact same spread.
        self.retry_jitter = max(0.0, float(retry_jitter))
        self.retry_seed = retry_seed
        self._reject_seq = 0
        self._retry_lock = threading.Lock()
        # Admission estimator regime switch (ISSUE 11 satellite; the
        # ROADMAP item-3 carve-out): below this many recorded waits the
        # EWMA model estimates, at/above it the REAL wait_hist p99 does.
        self.wait_est_floor = max(1, int(wait_est_floor))
        # Load-shed level in [0, 0.9] (SLO breach hook, blit/monitor.py):
        # scales the concurrency budget and the admitted queue depth
        # down while an objective burns.
        self._shed = 0.0
        self.pool = pool
        self.timeline = timeline if timeline is not None else Timeline()
        self.clock = clock
        self._lock = threading.Lock()
        self._idle = threading.Condition(self._lock)
        # priority -> client -> FIFO of queued jobs; _rr keeps the
        # round-robin pick order of clients with queued work.
        self._queues: Dict[int, Dict[str, Deque[Job]]] = {}
        self._rr: Dict[int, Deque[str]] = {}
        self._queued: Dict[int, int] = {}
        self._running = 0
        # Capacity currently held by session-length (unbounded) jobs —
        # a subset of _running, reported via held()/stats so operators
        # see how much budget live sessions pin (ISSUE 12 satellite).
        # _held_queued tracks hold jobs still WAITING for a slot, PER
        # PRIORITY: they too must stay out of the deadline estimator's
        # work-ahead (a queued session is not "one EWMA-length job
        # ahead of you"), and the subtraction must follow the same
        # priority filter as the queue sum it corrects.
        self._held = 0
        self._held_queued: Dict[int, int] = {}
        self._closed = False
        # EWMA of job service seconds — the wait estimator's unit cost.
        self._svc_ewma = 0.0
        self._svc_n = 0
        # Bounded wait distribution (ISSUE 5 satellite): the old per-sample
        # list grew for the life of the scheduler; HistogramStats holds 64
        # counters forever, merges into fleet reports, and keeps the
        # {"p50","p99","n"} report shape.
        self.wait_hist = HistogramStats()
        self.counts: Dict[str, int] = {
            "submitted": 0, "dispatched": 0, "rejected": 0,
            "cancelled": 0, "failed": 0,
        }

    # -- capacity ----------------------------------------------------------
    def shed(self, fraction: float) -> None:
        """Tighten (or relax) admission by a load-shed fraction in
        ``[0, 0.9]`` — the SLO breach action
        (:meth:`blit.monitor.BurnRateEvaluator.attach_scheduler`): the
        concurrency budget and the admitted queue depth both scale by
        ``1 - fraction`` while shed, so an overloaded service refuses
        work at the door instead of queueing latency it already cannot
        serve.  ``shed(0.0)`` restores full admission."""
        f = min(0.9, max(0.0, float(fraction)))
        changed = f != self._shed
        self._shed = f
        self.timeline.gauge("sched.shed", f)
        if changed:
            self.timeline.count("sched.shed_change")
            if f:
                log.warning("load shed engaged: admission scaled to "
                            "%.0f%%", (1.0 - f) * 100)
            else:
                log.info("load shed released: full admission restored")

    def shed_level(self) -> float:
        return self._shed

    def _shed_queue_depth(self) -> int:
        """The per-priority queue bound under the current shed level."""
        return max(1, int(self.queue_depth * (1.0 - self._shed)))

    def effective_budget(self) -> int:
        """The concurrency budget RIGHT NOW: the base budget scaled down
        by the current load-shed level (SLO breach hook) and by the
        fraction of degraded (breaker-open) hosts when a pool is
        attached; never below 1 (a fully degraded cluster still probes
        forward instead of wedging the queue)."""
        base = max(1, int(self.max_concurrency * (1.0 - self._shed)))
        if self.pool is None:
            return base
        health = self.pool.health()
        total = len(health)
        if total == 0:
            return base
        # Only a fully CLOSED breaker restores budget: a half-open host
        # is still degraded (one probe call is deciding its fate), so a
        # recovered-then-flaky host re-trips without ever having flapped
        # the budget back up (ISSUE 12 satellite).
        healthy = sum(1 for h in health if h.get("state") == "closed")
        return max(1, (base * healthy) // total)

    def depth(self) -> int:
        """Total queued jobs across every priority."""
        with self._lock:
            return sum(self._queued.values())

    def running(self) -> int:
        with self._lock:
            return self._running

    def held(self) -> int:
        """Concurrency slots pinned by session-length capacity holds
        (running jobs submitted with ``hold=True``)."""
        with self._lock:
            return self._held

    def est_wait_s(self, priority: int) -> float:
        """Expected queue wait for a NEW job at ``priority``.

        Two regimes (ISSUE 11 satellite — the ROADMAP item-3 carve-out):
        once ``wait_hist`` holds at least ``wait_est_floor`` recorded
        waits, the estimate is the REAL observed p99 queue wait (the
        tail the caller would actually risk — telemetry-hist-driven
        admission); below the floor it falls back to the EWMA model
        (work ahead x mean service time / budget), which is all a cold
        scheduler has.  Zero either way when nothing is ahead — an
        empty scheduler's history predicts nothing about an empty
        queue."""
        with self._lock:
            # Session-length holds are NOT work ahead — they never
            # finish "soon", so counting them (running OR still queued)
            # would reject every deadline the moment a live session
            # attaches.  They do pin capacity, which the budget term
            # below accounts.
            ahead = max(
                0,
                (self._running - self._held) + sum(
                    n for p, n in self._queued.items() if p <= priority
                ) - sum(n for p, n in self._held_queued.items()
                        if p <= priority),
            )
            held = self._held
            svc = self._svc_ewma
            n = self.wait_hist.n
            p99 = (self.wait_hist.percentile(0.99)
                   if n >= self.wait_est_floor else None)
        budget_free = self.effective_budget() - held
        if budget_free <= 0:
            # EVERY slot is pinned by session-length holds: bounded
            # work cannot start until a session ends, which the
            # estimator cannot bound — infinite, so deadline admission
            # rejects at the door instead of queueing a dead promise.
            return float("inf")
        if ahead == 0:
            return 0.0
        if p99 is not None:
            return p99
        return (ahead * svc) / budget_free

    def _retry_after_s(self, est: float) -> float:
        """The jittered ``retry_after_s`` for one rejection: the
        deterministic estimate spread by the RetryPolicy jitter rule
        (``est * (1 ± jitter)``) so simultaneously rejected clients do
        not return simultaneously.  Its own tiny lock, not the
        scheduler's: the service layer calls this for its own refusals
        (draining) while the scheduler lock may be held elsewhere."""
        base = max(0.1, est)
        if not self.retry_jitter:
            return base
        with self._retry_lock:
            k = self._reject_seq
            self._reject_seq += 1
        u = (random.Random(self.retry_seed * 1_000_003 + k).random()
             if self.retry_seed is not None else random.random())
        return max(0.05, base * (1.0 + self.retry_jitter * (2.0 * u - 1.0)))

    # -- submission --------------------------------------------------------
    def submit(
        self,
        fn: Callable[[], object],
        *,
        priority: int = 1,
        client: str = "anon",
        deadline_s: Optional[float] = None,
        hold: bool = False,
        on_drop: Optional[Callable[[BaseException], None]] = None,
    ) -> Job:
        """Admit ``fn`` for execution, or raise :class:`Overloaded`.

        ``deadline_s`` is the caller's patience: a job whose estimated
        queue wait already exceeds it is rejected at the door (the caller
        finds out NOW, not after the deadline burned in a queue).

        ``hold=True`` declares a session-length capacity hold (a LIVE
        job, ISSUE 12 satellite): the job consumes a concurrency slot
        for as long as the session records, but its (unbounded) service
        time never feeds the EWMA model and it is excluded from the
        deadline estimator's work-ahead count — the scheduler stops
        assuming bounded jobs.  ``held()``/``stats()`` report the
        pinned capacity."""
        if self._closed:
            raise RuntimeError("scheduler is closed")
        now = self.clock()
        est = self.est_wait_s(priority)
        with self._lock:
            depth_cap = self._shed_queue_depth()
            if self._queued.get(priority, 0) >= depth_cap:
                self.counts["rejected"] += 1
                self.timeline.count("sched.rejected")
                shed = (f", shedding {self._shed * 100:.0f}%"
                        if self._shed else "")
                raise Overloaded(
                    f"priority-{priority} queue full "
                    f"({depth_cap} jobs{shed}); try later",
                    retry_after_s=self._retry_after_s(est),
                )
            if deadline_s is not None and est > deadline_s:
                self.counts["rejected"] += 1
                self.timeline.count("sched.rejected")
                raise DeadlineExpired(
                    f"deadline {deadline_s:.3f}s unmeetable: estimated "
                    f"queue wait {est:.3f}s",
                    retry_after_s=self._retry_after_s(est),
                )
            job = Job(fn, priority, client, deadline_s, now, held=hold,
                      on_drop=on_drop)
            per_client = self._queues.setdefault(priority, {})
            q = per_client.get(client)
            if q is None:
                q = per_client[client] = deque()
                self._rr.setdefault(priority, deque())
            if client not in self._rr[priority]:
                self._rr[priority].append(client)
            q.append(job)
            self._queued[priority] = self._queued.get(priority, 0) + 1
            if job.held:
                self._held_queued[priority] = (
                    self._held_queued.get(priority, 0) + 1)
            self.counts["submitted"] += 1
            self.timeline.gauge("sched.queue_depth",
                                sum(self._queued.values()))
            self._dispatch_locked()
        return job

    # -- dispatch ----------------------------------------------------------
    def _pop_next_locked(self) -> Optional[Job]:
        """The next job by (priority asc, round-robin across clients)."""
        for priority in sorted(self._queues):
            rr = self._rr.get(priority)
            per_client = self._queues[priority]
            while rr:
                client = rr.popleft()
                q = per_client.get(client)
                if not q:
                    per_client.pop(client, None)
                    continue
                job = q.popleft()
                if q:
                    rr.append(client)  # more queued: back of the RR ring
                else:
                    per_client.pop(client, None)
                self._queued[priority] -= 1
                return job
        return None

    def _dispatch_locked(self) -> None:
        # One budget snapshot per dispatch round: effective_budget() walks
        # pool.health() (a breaker-lock acquisition per worker), too heavy
        # to re-evaluate per drained job while holding the scheduler lock.
        budget = self.effective_budget()
        while self._running < budget:
            job = self._pop_next_locked()
            if job is None:
                return
            if (job.deadline_s is not None
                    and self.clock() - job.submitted_at > job.deadline_s):
                # The deadline burned while the job sat queued (ISSUE 14
                # acceptance): an already-dead request is NEVER computed
                # — it is failed here, at dispatch, without a slot or
                # (fleet path) a peer ever touching it.
                if job.held:
                    self._held_queued[job.priority] -= 1
                job.state = "done"
                job.finished_at = self.clock()
                exc = DeadlineExpired(
                    f"deadline {job.deadline_s:.3f}s expired after "
                    f"{self.clock() - job.submitted_at:.3f}s in queue")
                job._exc = exc
                self.counts["expired"] = self.counts.get("expired", 0) + 1
                self.timeline.count("sched.expired")
                job._done.set()
                if job.on_drop is not None:
                    # On its own thread, exactly as fn would have run:
                    # the hook reaches back into the service layer
                    # (its lock), which may be held by whoever called
                    # submit() into this dispatch round.
                    threading.Thread(
                        target=self._run_drop, args=(job, exc),
                        name=f"blit-serve-drop-{job.client}",
                        daemon=True).start()
                continue
            job.state = "running"
            job.started_at = self.clock()
            self._running += 1
            if job.held:
                self._held_queued[job.priority] -= 1
                self._held += 1
                self.timeline.gauge("sched.held", self._held)
            self.counts["dispatched"] += 1
            wait = job.started_at - job.submitted_at
            self.wait_hist.observe(wait)
            self.timeline.gauge("sched.wait_s", wait)
            self.timeline.observe("sched.wait_s", wait)
            self.timeline.gauge("sched.running", self._running)
            threading.Thread(
                target=self._run, args=(job,),
                name=f"blit-serve-{job.client}", daemon=True,
            ).start()

    def _run_drop(self, job: Job, exc: BaseException) -> None:
        try:
            job.on_drop(exc)
        except Exception:  # noqa: BLE001 — a drop hook must not wedge
            log.warning("on_drop hook for client %r failed", job.client,
                        exc_info=True)

    def _run(self, job: Job) -> None:
        t0 = time.perf_counter()
        try:
            faults.fire("sched.dispatch", key=job.client)
            with self.timeline.stage("sched.run", byte_free=True):
                job._result = job.fn()
        except BaseException as e:  # noqa: BLE001 — delivered via result()
            job._exc = e
            with self._lock:
                self.counts["failed"] += 1
            self.timeline.count("sched.failed")
        finally:
            dt = time.perf_counter() - t0
            with self._lock:
                if job.held:
                    # A session's duration is the RECORDING's, not the
                    # machinery's: folding it into the EWMA would make
                    # the deadline estimator reject every bounded job
                    # after one long session (ISSUE 12 satellite).
                    self._held -= 1
                    self.timeline.gauge("sched.held", self._held)
                else:
                    # EWMA toward recent service times (alpha 0.3),
                    # seeded by the first observation — the wait
                    # estimator's unit cost.
                    self._svc_n += 1
                    self._svc_ewma = (
                        dt if self._svc_n == 1
                        else 0.7 * self._svc_ewma + 0.3 * dt
                    )
                self._running -= 1
                self.timeline.gauge("sched.running", self._running)
                job.state = "done"
                job.finished_at = self.clock()
                self._dispatch_locked()
                self._idle.notify_all()
            job._done.set()

    # -- cancellation / teardown ------------------------------------------
    def cancel(self, job: Job) -> bool:
        """Cancel a still-QUEUED job, releasing its queue slot (True).
        Running jobs are not interrupted (False) — Python offers no safe
        preemption; the caller simply stops waiting."""
        with self._lock:
            if job.state != "queued":
                return False
            q = self._queues.get(job.priority, {}).get(job.client)
            if q is None or job not in q:
                return False
            q.remove(job)
            self._queued[job.priority] -= 1
            if job.held:
                self._held_queued[job.priority] -= 1
            job.state = "cancelled"
            self.counts["cancelled"] += 1
            self.timeline.count("sched.cancelled")
        job._exc = Cancelled("cancelled while queued")
        job._done.set()
        return True

    def wait_percentiles(self) -> Dict[str, float]:
        """p50/p99 of the recorded queue waits (seconds; 0 when empty) —
        bucket estimates from the bounded histogram (good to a factor of
        2), same ``{"p50","p99","n"}`` shape as the old exact-sample
        report."""
        with self._lock:
            # Under the lock: observe() runs inside _dispatch_locked, so
            # the counts/envelope pair stays consistent for the walk.
            h = self.wait_hist
            return {"p50": h.percentile(0.50), "p99": h.percentile(0.99),
                    "n": h.n}

    def drain(self, timeout: Optional[float] = 30.0,
              cancel_queued: bool = True) -> int:
        """Graceful shutdown (ISSUE 14 satellite: the SIGTERM path):
        refuse new work NOW, optionally cancel everything still queued
        (delivering :class:`Cancelled` — a drain has no future in which
        to run them), and wait for the running jobs to finish.  Returns
        the number of queued jobs cancelled.  In-flight work always
        completes — drain never interrupts a running reduction; live
        sessions (``hold=True``) end when their SOURCES are closed,
        which is :meth:`blit.serve.service.ProductService.drain`'s job
        before it calls here."""
        self._closed = True
        cancelled = 0
        if cancel_queued:
            with self._lock:
                jobs: list = []
                for per_client in self._queues.values():
                    for q in per_client.values():
                        jobs.extend(q)
            for job in jobs:
                if self.cancel(job):
                    cancelled += 1
        self.close(timeout)
        return cancelled

    def close(self, timeout: Optional[float] = 30.0) -> None:
        """Refuse new work and wait for queued+running jobs to drain."""
        self._closed = True
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._idle:
            while self._running > 0 or sum(self._queued.values()) > 0:
                if deadline is not None and time.monotonic() >= deadline:
                    log.warning(
                        "scheduler close timed out with %d running / "
                        "%d queued jobs", self._running,
                        sum(self._queued.values()),
                    )
                    return
                self._idle.wait(timeout=None if deadline is None else 0.1)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
