"""Failure-domain-tolerant fleet front door (ISSUE 14 tentpole).

ROADMAP item 3's missing half: `blit/serve` coalesces, caches and
admission-controls — in ONE process.  This module puts a front door in
front of N cache/compute peers (:class:`blit.serve.http.PeerServer`
processes) and makes the resulting service survive its hosts:

- **Consistent-hash routing** (:class:`~blit.serve.ring.HashRing`):
  every request's PR-3 content-addressed fingerprint maps to an OWNER
  peer plus ``replicas - 1`` successors.  Fingerprints are
  order-insensitive over the raw members, so two doors (or one across
  restarts) agree on ownership with no coordination and cross-host
  dedupe is structural — identical requests, however their globs
  ordered the members, always land on the same owner's cache, where
  the peer's own single-flight machinery coalesces them.
- **Failure-domain tolerance**: peer liveness is judged by heartbeat
  leases (:class:`blit.recover.LeaseWatch` — the scan supervisor's
  staleness discipline applied to serving peers); a silent peer is
  EJECTED from the ring within the lease TTL and its key range
  re-routes to the replicas, rejoining when beats resume.  Per-peer
  :class:`~blit.faults.CircuitBreaker`\\ s fail fast on a flapping peer
  between lease verdicts, and hot entries are CACHE-WARMED onto
  replicas (``hot_hits`` threshold + drain-time hints), so losing the
  owner degrades hit-rate, not correctness.
- **Hedged reads**: when the owner has not answered within its own
  LIVE p99 (per-peer :class:`~blit.observability.HistogramStats`, the
  PR 5 discipline — never a guessed constant once history exists), the
  request is duplicated to the next replica and the first answer wins.
  At most ONE hedge per request bounds duplicate compute at 2x on the
  hedged slice; ``fleet.hedge`` / ``fleet.hedge.win`` /
  ``fleet.hedge.dup_done`` ride ``/metrics``.
- **Deadline propagation**: the caller's ``deadline_s`` is checked at
  the door before EVERY dispatch (an already-dead request never
  reaches a peer — the acceptance pin) and travels on the wire into
  the peer :class:`~blit.serve.scheduler.Scheduler`'s deadline-aware
  admission and dispatch-time expiry, so no layer computes work whose
  requester has already given up.
- **Graceful drain**: :meth:`FleetFrontDoor.drain` refuses new
  requests, lets in-flight ones finish, and hands the hottest
  fingerprints' recipes to their owner/replica peers as ``/warm``
  hints, so a door restart does not cold-start the fleet's working
  set.

The door is deliberately CACHE-LESS and QUEUE-LESS: peers own the
two-tier caches and the admission-controlled schedulers; the door owns
placement, liveness and retries.  That keeps its failure mode boring —
a restarted door re-derives the whole routing state from config plus
the lease dir in one poll interval.
"""

from __future__ import annotations

import json
import logging
import os
import queue
import threading
import time
from collections import OrderedDict
from typing import Callable, Dict, Iterable, List, Optional, Tuple

import numpy as np

from blit import faults, observability
from blit.config import DEFAULT, SiteConfig, fleet_defaults
from blit.faults import CircuitBreaker
from blit.observability import (
    HistogramStats,
    StallWatchdog,
    Timeline,
    flight_recorder,
    hostname,
    merge_fleet,
    render_prometheus,
)
from blit.serve.http import (
    TIER_HEADER,
    WIRE_CTYPE,
    ConnectionPool,
    decode_product,
    decode_product_wire,
    http_json,
    http_request,
    retry_after_from,
    trace_headers,
    wire_request,
)
from blit.serve.ring import HashRing
from blit.serve.scheduler import DeadlineExpired, Overloaded

log = logging.getLogger("blit.serve.fleet")

# The fleet plane's latency histograms (the MESH_HISTS convention).
# serialize_s lands on the PEER's timeline (it encodes), the rest on
# the door's; wire_bytes is a histogram so .total carries the exact
# byte sum the bench's GB/s needs.  catalog.lookup_s times the door's
# archive-catalog resolutions and document asks (ISSUE 19) — the
# archive-day bench's catalog-lookup p50/p99 source.
FLEET_HISTS = ("fleet.request_s", "fleet.peer_s", "fleet.detect_s",
               "fleet.serialize_s", "fleet.deserialize_s",
               "fleet.wire_bytes", "catalog.lookup_s")


class FleetError(RuntimeError):
    """Every routable replica failed (or none exist) for a request."""


class PeerHTTPError(OSError):
    """A peer answered outside the serve contract (HTTP 5xx that is not
    an Overloaded/deadline mapping) — an ``OSError`` so breakers and
    transient-retry classification treat it like a failing host."""


class _HttpWatch:
    """Liveness fallback when no lease dir is shared with the peers:
    the :class:`~blit.observability.StallWatchdog` beaten by successful
    ``/healthz`` fetches — same staleness contract, HTTP as the beat
    transport."""

    def __init__(self, name: str, ttl_s: float):
        self.wd = StallWatchdog(ttl_s, f"blit-fleet-{name}",
                                what="a dead peer stops answering "
                                     "/healthz")
        self.seen = False

    def observe(self) -> None:  # the LeaseWatch poll surface
        pass

    def note_health(self, ok: bool) -> None:
        if ok:
            self.wd.beat()
            self.seen = True

    def stalled(self) -> bool:
        return self.seen and self.wd.stalled()

    def fresh(self) -> bool:
        """Beating and not stale (the LeaseWatch surface, ISSUE 17)."""
        return self.seen and not self.wd.stalled()

    def age_s(self) -> float:
        return self.wd.age_s()


class _Peer:
    """One peer's routing state: breaker, live latency histogram,
    lease/HTTP liveness watch, last fetched health document.

    ``standby`` marks an elastic-capacity peer (ISSUE 17): process up,
    lease beating, deliberately NOT in the ring until the controller
    admits it.  ``retired`` marks a peer scaled in on purpose — neither
    is a casualty, so the liveness loop must not auto-rejoin them and
    the health document must not count them degraded."""

    def __init__(self, name: str, url: str, watch, *,
                 breaker_threshold: int, breaker_cooldown_s: float):
        self.name = name
        self.url = url
        self.watch = watch
        self.breaker = CircuitBreaker(threshold=breaker_threshold,
                                      cooldown_s=breaker_cooldown_s)
        self.hist = HistogramStats()
        self.in_ring = True
        self.standby = False
        self.retired = False
        self.last_health: Optional[Dict] = None
        self.requests = 0
        self.failures = 0

    def snapshot(self) -> Dict:
        return {
            "url": self.url,
            "in_ring": self.in_ring,
            "standby": self.standby,
            "retired": self.retired,
            "breaker": self.breaker.snapshot()["state"],
            "requests": self.requests,
            "failures": self.failures,
            "p50_s": round(self.hist.percentile(0.50), 6),
            "p99_s": round(self.hist.percentile(0.99), 6),
            "n": self.hist.n,
            "lease_age_s": round(self.watch.age_s(), 3),
        }


class FleetFrontDoor:
    """The fleet's routing/liveness brain (module docstring).  Drive it
    directly (``get()``) or serve it over HTTP with
    :class:`blit.serve.http.FrontDoorServer`.

    ``peers`` maps peer name → base URL.  ``lease_dir`` (shared with
    the peers) switches liveness to heartbeat-lease files; without it,
    successful ``/healthz`` fetches are the beat.  ``proc_of`` maps
    peer name → its lease proc index (default: enumeration order).
    ``start()`` runs the liveness loop; ``close()`` stops it."""

    def __init__(self, peers: Dict[str, str], *,
                 lease_dir: Optional[str] = None,
                 proc_of: Optional[Dict[str, int]] = None,
                 config: SiteConfig = DEFAULT,
                 timeline: Optional[Timeline] = None,
                 replicas: Optional[int] = None,
                 peer_ttl_s: Optional[float] = None,
                 poll_s: Optional[float] = None,
                 health_poll_s: Optional[float] = None,
                 hedge_floor_s: Optional[float] = None,
                 hedge_min_n: Optional[int] = None,
                 hot_hits: Optional[int] = None,
                 request_timeout_s: float = 300.0,
                 clock: Callable[[], float] = time.monotonic,
                 catalog=None):
        d = fleet_defaults(config)
        self.replicas = int(replicas if replicas is not None
                            else d["replicas"])
        self.peer_ttl_s = float(peer_ttl_s if peer_ttl_s is not None
                                else d["peer_ttl_s"])
        self.poll_s = float(poll_s if poll_s is not None else d["poll_s"])
        self.health_poll_s = float(
            health_poll_s if health_poll_s is not None
            else d["health_poll_s"])
        self.hedge_floor_s = float(
            hedge_floor_s if hedge_floor_s is not None
            else d["hedge_floor_s"])
        self.hedge_min_n = int(hedge_min_n if hedge_min_n is not None
                               else d["hedge_min_n"])
        self.hot_hits = int(hot_hits if hot_hits is not None
                            else d["hot_hits"])
        # Hot-path data plane (ISSUE 16): which product wire to ask
        # peers for ("binary" | "json" — SiteConfig.fleet_wire /
        # BLIT_FLEET_WIRE), whether to advertise deflate, and the
        # bounded per-peer keep-alive pool every hop rides.
        self.wire = str(d["wire"])
        self._wire_deflate = bool(d["wire_deflate"])
        self.request_timeout_s = float(request_timeout_s)
        self.clock = clock
        self.timeline = timeline if timeline is not None else Timeline()
        self.pool = ConnectionPool(max_per_peer=d["pool_conns"],
                                   timeline=self.timeline)
        self.lease_dir = lease_dir
        self.ring = HashRing(peers, vnodes=d["vnodes"],
                             replicas=self.replicas)
        self._breaker_threshold = config.breaker_threshold
        self._breaker_cooldown_s = config.breaker_cooldown_s
        self._peers: Dict[str, _Peer] = {}
        for i, (name, url) in enumerate(peers.items()):
            proc = (proc_of or {}).get(name, i)
            self._peers[name] = _Peer(
                name, url, self._make_watch(name, proc),
                breaker_threshold=self._breaker_threshold,
                breaker_cooldown_s=self._breaker_cooldown_s)
        # Elastic resize state (ISSUE 17): set by the FleetController
        # around a membership flip; health() answers "resizing" while
        # it is non-None.
        self.resize_reason: Optional[str] = None
        self._lock = threading.Lock()
        self._drain_cond = threading.Condition(self._lock)
        self._inflight = 0
        self._draining = False
        # Hotness: fp -> (hits, recipe), LRU-bounded — the cache-warm
        # replication trigger and the drain-hint source.
        self._hot: "OrderedDict[str, Tuple[int, Dict]]" = OrderedDict()
        self._hot_max = 4096
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._last_health_fetch = 0.0
        # Per-request access records (ISSUE 15 tentpole #2): the door
        # writes exactly one line per request — served, refused at the
        # drain latch, deadline-expired or failed — with the routing
        # outcome a peer-side record cannot know (routed peer, hedge
        # verdict).  None (one attribute test per request) unless
        # BLIT_REQUEST_LOG / SiteConfig.request_log_dir is set.
        # (request_log_for also applies the config's exemplars knob.)
        self.request_log = observability.request_log_for("door", config)
        # Door-side archive catalog (ISSUE 19 tentpole #1): resolves
        # by-(session, scan) logical asks into the explicit member-path
        # recipe BEFORE ring routing — so a logical ask fingerprints
        # (and routes, dedupes, coalesces) identically to its explicit
        # twin.  Built when BLIT_CATALOG_ROOT / SiteConfig.catalog_root
        # names a tree, or passed in ready-made.
        self.catalog = catalog
        if self.catalog is None:
            from blit.config import catalog_defaults

            if catalog_defaults(config)["enabled"]:
                from blit.serve.catalog import CatalogIndex

                self.catalog = CatalogIndex(config=config,
                                            timeline=self.timeline)

    def _make_watch(self, name: str, proc: int):
        if self.lease_dir is not None:
            from blit.recover import LeaseWatch

            return LeaseWatch(self.lease_dir, proc, self.peer_ttl_s,
                              grace_s=self.peer_ttl_s)
        return _HttpWatch(name, self.peer_ttl_s)

    # -- liveness ----------------------------------------------------------
    def start(self) -> "FleetFrontDoor":
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._watch_loop, name="blit-fleet-watch",
                daemon=True)
            self._thread.start()
        return self

    def _watch_loop(self) -> None:
        while not self._stop.wait(self.poll_s):
            try:
                self.observe()
            except Exception:  # noqa: BLE001 — liveness must not die
                log.warning("fleet watch tick failed", exc_info=True)

    def observe(self) -> None:
        """One liveness tick (the watch loop's body; tests drive it
        directly): observe every lease, eject stale peers, rejoin
        recovered ones, refresh health documents on their own
        cadence."""
        fetch_health = False
        now = time.monotonic()
        if now - self._last_health_fetch >= self.health_poll_s:
            self._last_health_fetch = now
            fetch_health = True
        for p in self._peers.values():
            p.watch.observe()
            if fetch_health:
                self._fetch_health(p)
            if p.in_ring and p.watch.stalled():
                self._eject(p, f"lease stale {p.watch.age_s():.2f}s")
            elif (not p.in_ring and not p.standby and not p.retired
                  and p.watch.seen and not p.watch.stalled()):
                # Standby and retired peers are out of the ring ON
                # PURPOSE (ISSUE 17) — only the elastic controller
                # admits them; a fresh lease alone must not.
                self._rejoin(p)

    def _fetch_health(self, p: _Peer) -> None:
        try:
            status, _, body = http_json("GET", p.url, "/healthz",
                                        timeout=2.0, pool=self.pool)
            ok = status == 200 and isinstance(body, dict)
            p.last_health = body if ok else None
        except OSError:
            ok = False
            p.last_health = None
        if isinstance(p.watch, _HttpWatch):
            p.watch.note_health(ok)

    def _eject(self, p: _Peer, reason: str) -> None:
        """Remove a failed peer from the ring: its key range re-routes
        to the replica successors ON THE NEXT LOOKUP (consistent
        hashing makes re-routing a no-op for everyone else)."""
        if not self.ring.remove(p.name):
            return
        p.in_ring = False
        # Sever the idle keep-alives to the departed peer (ISSUE 17
        # satellite): a pooled socket to a dead host would eat one
        # failed write per request until the LIFO stack drained.
        self.pool.evict_peer(p.url)
        self.timeline.count("fleet.eject")
        # Detection latency (the chaos drill's budget assertion): how
        # stale the lease was when we acted — age at detection, the
        # recover-plane convention.
        self.timeline.observe("fleet.detect_s", p.watch.age_s())
        flight_recorder().event("fleet", "eject", peer=p.name,
                                reason=reason)
        # An eject IS an incident: one self-contained forensics bundle
        # (ISSUE 20) when BLIT_INCIDENT_DIR arms the bundler — the
        # door's timeline + recent requests/spans around the kill.
        try:
            from blit.history import maybe_incident

            maybe_incident(
                "fleet-eject",
                f"fleet ejected peer {p.name}: {reason}",
                alert={"t": time.time(), "class": "fleet",
                       "peer": p.name, "reason": reason},
                timeline=self.timeline)
        except Exception:  # noqa: BLE001 — paging must not break eject
            log.warning("eject incident bundle failed", exc_info=True)
        log.warning("fleet: ejected peer %s (%s); %d peer(s) remain",
                    p.name, reason, len(self.ring))

    def _rejoin(self, p: _Peer) -> None:
        if not self.ring.add(p.name):
            return
        p.in_ring = True
        p.breaker.record_success()  # fresh start: the lease vouches
        self.timeline.count("fleet.rejoin")
        flight_recorder().event("fleet", "rejoin", peer=p.name)
        log.warning("fleet: peer %s rejoined the ring", p.name)

    # -- elastic membership (ISSUE 17) -------------------------------------
    def add_standby(self, name: str, url: str, *,
                    proc: Optional[int] = None) -> _Peer:
        """Pre-register an elastic standby: lease-watched like any peer
        (its beats are observed, its health fetched) but NOT in the
        ring — no request routes to it until :meth:`admit_peer`.
        ``proc`` is its lease proc index (default: registration
        order)."""
        with self._lock:
            idx = proc if proc is not None else len(self._peers)
            p = _Peer(name, url.rstrip("/"), self._make_watch(name, idx),
                      breaker_threshold=self._breaker_threshold,
                      breaker_cooldown_s=self._breaker_cooldown_s)
            p.in_ring = False
            p.standby = True
            self._peers[name] = p
        self.timeline.count("fleet.standby")
        flight_recorder().event("fleet", "standby", peer=name)
        return p

    def admit_peer(self, name: str) -> bool:
        """Flip a standby (or retired) peer INTO the ring — the elastic
        scale-out membership flip, called by the FleetController only
        after the warm handoff acked or its deadline burned."""
        p = self._peers[name]
        p.standby = False
        p.retired = False
        if not self.ring.add(name):
            return False
        p.in_ring = True
        p.breaker.record_success()  # fresh start: the controller vouches
        self.timeline.count("fleet.admit")
        flight_recorder().event("fleet", "admit", peer=name)
        log.warning("fleet: peer %s admitted to the ring (scale-out); "
                    "%d peer(s)", name, len(self.ring))
        return True

    def retire_peer(self, name: str) -> bool:
        """Remove a drained peer from the ring ON PURPOSE — the elastic
        scale-in flip.  Unlike ejection this is not a casualty: the
        peer is marked ``retired`` so a still-beating lease cannot
        auto-rejoin it, and its pooled keep-alives are severed so no
        later request is written to a departed peer's dead socket."""
        p = self._peers[name]
        p.retired = True
        p.standby = False
        removed = self.ring.remove(name)
        p.in_ring = False
        self.pool.evict_peer(p.url)
        if removed:
            self.timeline.count("fleet.retire")
            flight_recorder().event("fleet", "retire", peer=name)
            log.warning("fleet: peer %s retired from the ring "
                        "(scale-in); %d peer(s) remain", name,
                        len(self.ring))
        return removed

    # -- routing -----------------------------------------------------------
    def _remaining(self, t0: float,
                   deadline_s: Optional[float]) -> Optional[float]:
        if deadline_s is None:
            return None
        return float(deadline_s) - (self.clock() - t0)

    def _fetch_timeout(self, t0: float,
                       deadline_s: Optional[float]) -> float:
        rem = self._remaining(t0, deadline_s)
        if rem is None:
            return self.request_timeout_s
        return max(0.05, min(self.request_timeout_s, rem))

    def _hedge_delay(self, p: _Peer) -> float:
        """When to try a second replica: the peer's LIVE p99 once
        enough history exists (the PR 5 telemetry-hist discipline),
        else the configured floor — never a guess dressed as a
        measurement."""
        if p.hist.n >= self.hedge_min_n:
            return max(self.hedge_floor_s, p.hist.percentile(0.99))
        return self.hedge_floor_s

    def get(self, request, *, priority: int = 1, client: str = "anon",
            deadline_s: Optional[float] = None
            ) -> Tuple[Dict, np.ndarray]:
        """Serve one product request through the fleet: route to the
        fingerprint's owner, hedge to a replica past the live p99, fail
        over on refusal/death, propagate the deadline every hop.
        Raises :class:`~blit.serve.scheduler.Overloaded` /
        :class:`~blit.serve.scheduler.DeadlineExpired` /
        :class:`FleetError` (every replica failed).

        The whole request runs inside a ``fleet.request`` span
        (ISSUE 15): peer dispatches become child spans carried across
        the wire, the hedge verdict lands on this span's attrs, and one
        access record is written per call whatever the outcome."""
        t0 = self.clock()
        t_req = time.perf_counter()
        rid = observability.new_id()
        tr = observability.tracer()
        status, code, fp, nbytes = "error", 500, None, 0
        trace_id: Optional[str] = None
        outcome: Dict = {}
        # The LOGICAL address (ISSUE 19): captured before resolution
        # rewrites the request, so access records group archive traffic
        # by (session, scan) even though the wire carries member paths.
        sess = getattr(request, "session", None)
        scan = getattr(request, "scan", None)
        is_catalog = getattr(request, "kind", None) == "catalog"
        try:
            with tr.span("fleet.request", client=client) as sp:
                if sp is not None:
                    trace_id = sp.trace_id
                with self._lock:
                    if self._draining:
                        self.timeline.count("fleet.rejected")
                        raise Overloaded(
                            "front door is draining; retry against "
                            "the replacement", retry_after_s=1.0)
                    self._inflight += 1
                try:
                    if sess is not None:
                        request = self._resolve(request)
                    wire = wire_request(request, priority=priority,
                                        client=client,
                                        deadline_s=deadline_s)
                    if is_catalog:
                        from blit.serve.catalog import catalog_fingerprint

                        fp = catalog_fingerprint(
                            (request.raw or "").strip("/"))
                    else:
                        from blit.serve.cache import fingerprint_for

                        fp = fingerprint_for(request.reducer(),
                                             request.raw_source)
                    self.timeline.count("fleet.requests")
                    header, data = self._fetch(fp, wire, t0, deadline_s,
                                               rid=rid, outcome=outcome)
                    # Observed INSIDE the request span: the tail
                    # bucket's exemplar is this request's trace id
                    # (ISSUE 15 tentpole #3).
                    self.timeline.observe("fleet.request_s",
                                          time.perf_counter() - t_req)
                    if is_catalog:
                        # A catalog ask's whole round-trip IS the
                        # lookup — the archive-day bench's p50/p99.
                        self.timeline.observe(
                            "catalog.lookup_s",
                            time.perf_counter() - t_req)
                    nbytes = data.nbytes
                    status, code = "ok", 200
                    if sp is not None:
                        sp.attrs = dict(
                            sp.attrs or {}, fp=fp[:16],
                            **{k: v for k, v in outcome.items()
                               if v is not None})
                    if not is_catalog:
                        # Catalog documents are query-addressed and
                        # regenerate on every ask — never warm-hinted.
                        self._note_hot(fp, wire["recipe"])
                    return header, data
                finally:
                    with self._drain_cond:
                        self._inflight -= 1
                        self._drain_cond.notify_all()
        except BaseException as e:
            from blit.serve.scheduler import classify_failure

            status, code = classify_failure(e)
            raise
        finally:
            if self.request_log is not None:
                dt = time.perf_counter() - t_req
                self.request_log.record(
                    rid=rid, trace=trace_id,
                    role="door", client=client, priority=priority,
                    fp=(fp[:16] if fp else None),
                    session=sess, scan=scan,
                    tier=outcome.get("tier"),
                    peer=outcome.get("peer"),
                    hedged=outcome.get("hedged"),
                    hedge_won=outcome.get("hedge_won"),
                    deadline_s=deadline_s,
                    deadline_left_s=(round(deadline_s - dt, 6)
                                     if deadline_s is not None else None),
                    status=status, code=code, bytes=nbytes,
                    duration_s=round(dt, 6))

    def _resolve(self, request):
        """Resolve a by-(session, scan) logical ask into its explicit
        member-path twin AT THE DOOR (ISSUE 19 tentpole #1) — before
        ring routing, so both spellings of one logical product share a
        fingerprint, an owner and a single-flight group.  Misses raise
        :class:`~blit.serve.catalog.CatalogMiss` (the 404-class
        outcome); the lookup's latency feeds ``catalog.lookup_s``."""
        if self.catalog is None:
            raise FleetError(
                "session=/scan= addressing needs a door catalog "
                "(BLIT_CATALOG_ROOT / SiteConfig.catalog_root)")
        import dataclasses

        t = time.perf_counter()
        try:
            members = self.catalog.resolve(
                request.session, request.scan,
                band=request.band, bank=request.bank)
        finally:
            self.timeline.observe("catalog.lookup_s",
                                  time.perf_counter() - t)
        self.timeline.count("fleet.resolved")
        return dataclasses.replace(
            request, raw=tuple(members),
            session=None, scan=None, band=None, bank=None)

    def targets_for(self, fp: str) -> List[_Peer]:
        return [self._peers[n] for n in self.ring.owners(fp)]

    def _fetch(self, fp: str, wire: Dict, t0: float,
               deadline_s: Optional[float], rid: Optional[str] = None,
               outcome: Optional[Dict] = None
               ) -> Tuple[Dict, np.ndarray]:
        targets = self.targets_for(fp)
        if not targets:
            raise FleetError("no live peers in the ring")
        # The caller's ambient context (the fleet.request span): every
        # dispatch thread reactivates it so its fleet.dispatch span — and
        # the peer-side spans parented onto it across the wire — belong
        # to THIS request's trace (ISSUE 15 tentpole #1).
        ctx = observability.tracer().context()
        q: "queue.Queue" = queue.Queue()
        done = threading.Event()

        def run(p: _Peer, hedge: bool) -> None:
            try:
                res = self._fetch_one(p, wire, fp, t0, deadline_s,
                                      ctx=ctx, hedge=hedge, rid=rid)
                ok = True
            except BaseException as e:  # noqa: BLE001 — delivered below
                res, ok = e, False
            if ok and done.is_set():
                # The duplicate finished after the winner: its work ran
                # to completion (and warmed that peer's cache) — counted
                # so the bench can bound duplicate compute on the
                # hedged slice.
                self.timeline.count("fleet.hedge.dup_done")
            q.put((p, hedge, ok, res))

        idx = 0
        pending = 0

        def launch(hedge: bool) -> Optional[_Peer]:
            nonlocal idx, pending
            while idx < len(targets):
                p = targets[idx]
                idx += 1
                rem = self._remaining(t0, deadline_s)
                if rem is not None and rem <= 0:
                    return None  # the waiter raises DeadlineExpired
                if not p.breaker.allow():
                    self.timeline.count("fleet.skip_breaker")
                    continue
                if hedge:
                    self.timeline.count("fleet.hedge")
                pending += 1
                threading.Thread(target=run, args=(p, hedge),
                                 name=f"blit-fleet-{p.name}",
                                 daemon=True).start()
                return p
            return None

        rem = self._remaining(t0, deadline_s)
        if rem is not None and rem <= 0:
            # The acceptance pin: a request already dead at the front
            # door is REJECTED here — no peer is ever dispatched.
            self.timeline.count("fleet.deadline_expired")
            raise DeadlineExpired(
                f"deadline {deadline_s:.3f}s expired at the front door "
                f"after {self.clock() - t0:.3f}s; never dispatched")
        first = launch(hedge=False)
        if first is None:
            rem = self._remaining(t0, deadline_s)
            if rem is not None and rem <= 0:
                self.timeline.count("fleet.deadline_expired")
                raise DeadlineExpired(
                    f"deadline {deadline_s:.3f}s expired at the front "
                    "door; never dispatched")
            raise FleetError(
                f"no routable peer for {fp[:16]}… "
                f"({len(targets)} in ring, all breaker-blocked)")
        hedged = False
        last_exc: Optional[BaseException] = None
        hedge_delay = self._hedge_delay(first)
        while True:
            rem = self._remaining(t0, deadline_s)
            if not hedged and idx < len(targets):
                wait = (hedge_delay if rem is None
                        else min(hedge_delay, max(0.0, rem)))
            else:
                wait = (self.request_timeout_s if rem is None
                        else max(0.0, rem)) + 1.0
            try:
                p, was_hedge, ok, res = q.get(timeout=max(0.005, wait))
            except queue.Empty:
                if not hedged and idx < len(targets):
                    hedged = True
                    launch(hedge=True)  # first-wins from here on
                    continue
                if rem is not None and rem <= 0:
                    self.timeline.count("fleet.deadline_expired")
                    raise DeadlineExpired(
                        f"deadline {deadline_s:.3f}s expired waiting on "
                        "replicas") from last_exc
                raise FleetError(
                    f"no replica answered {fp[:16]}… within "
                    f"{self.request_timeout_s}s") from last_exc
            pending -= 1
            if ok:
                done.set()
                if was_hedge:
                    self.timeline.count("fleet.hedge.win")
                header, data, tier = res
                if outcome is not None:
                    # The routing verdict for the parent span + access
                    # record: who answered, from which tier, and
                    # whether the hedge won (ISSUE 15).
                    outcome.update(peer=p.name, tier=tier,
                                   hedged=1 if hedged else None,
                                   hedge_won=(1 if was_hedge else 0)
                                   if hedged else None)
                return header, data
            last_exc = res
            rem = self._remaining(t0, deadline_s)
            if isinstance(res, DeadlineExpired) and (rem is None
                                                    or rem > 0):
                # The PEER judged the deadline unmeetable — an
                # admission ESTIMATE over its own backlog, not a global
                # verdict: a replica holding the cache-warmed product
                # answers in milliseconds regardless of queue depth.
                # Only the door's own burned budget is terminal.
                res = Overloaded(str(res), retry_after_s=0.1)
                last_exc = res
            if isinstance(res, DeadlineExpired):
                raise res  # the budget itself is gone
            if type(res).__name__ == "CatalogMiss":
                raise res  # the ASK is wrong — no replica can fix that
            if isinstance(res, Overloaded):
                # Alive but refusing — the breaker stays untouched;
                # another replica may have capacity (or the cache).
                self.timeline.count("fleet.failover")
            else:
                if self._record_peer_failure(p):
                    log.warning("fleet: breaker tripped for peer %s "
                                "(%s)", p.name, res)
                self.timeline.count("fleet.failover")
            nxt = launch(hedge=False)
            if nxt is None and pending == 0:
                if rem is not None and rem <= 0:
                    # Out of replicas BECAUSE the budget burned during
                    # failover: that is a deadline verdict (504, final),
                    # not a fleet failure (500/503, retryable).
                    self.timeline.count("fleet.deadline_expired")
                    raise DeadlineExpired(
                        f"deadline {deadline_s:.3f}s expired during "
                        "failover") from last_exc
                if isinstance(last_exc, Overloaded):
                    raise last_exc
                raise FleetError(
                    f"every replica failed for {fp[:16]}…: "
                    f"{last_exc}") from last_exc

    def _record_peer_failure(self, p: _Peer) -> bool:
        p.failures += 1
        tripped = p.breaker.record_failure()
        if tripped:
            self.timeline.count("fleet.breaker_trip")
            flight_recorder().event("fleet", "breaker_trip", peer=p.name)
        return tripped

    def _fetch_one(self, p: _Peer, wire: Dict, fp: str, t0: float,
                   deadline_s: Optional[float], ctx: Optional[Dict] = None,
                   hedge: bool = False, rid: Optional[str] = None
                   ) -> Tuple[Dict, np.ndarray, Optional[str]]:
        """One peer round-trip → ``(header, data, tier)`` with the
        remaining deadline propagated ON THE WIRE (the peer's scheduler
        re-checks it at admission and dispatch), the live latency
        histogram fed either way, and the trace context carried as
        headers (ISSUE 15): the dispatch runs in its own
        ``fleet.dispatch`` span — hedges are sibling spans tagged
        ``hedge=1`` — whose context the peer reactivates, so peer-side
        spans parent onto this request across the process boundary."""
        tr = observability.tracer()
        with tr.activate(ctx), \
                tr.span("fleet.dispatch", peer=p.name,
                        hedge=1 if hedge else 0):
            faults.fire("fleet.route", key=p.name)
            doc = dict(wire)
            rem = self._remaining(t0, deadline_s)
            if rem is not None:
                doc["deadline_s"] = max(0.0, rem)
            p.requests += 1
            self.timeline.count("fleet.route")
            req_hdrs = trace_headers(hedge=hedge, rid=rid)
            req_hdrs["Content-Type"] = "application/json"
            if self.wire == "binary":
                # Negotiate the binary product wire (ISSUE 16): a peer
                # that can't speak it answers legacy JSON — decoded
                # below either way, bit-identically.
                req_hdrs["Accept"] = (
                    f"{WIRE_CTYPE}, application/json")
                if self._wire_deflate:
                    req_hdrs["Accept-Encoding"] = "deflate"
            t = time.perf_counter()
            try:
                status, hdrs, payload = http_request(
                    "POST", p.url, "/product",
                    body=json.dumps(doc).encode(),
                    timeout=self._fetch_timeout(t0, deadline_s),
                    headers=req_hdrs, pool=self.pool)
            finally:
                dt = time.perf_counter() - t
                p.hist.observe(dt)
                self.timeline.observe("fleet.peer_s", dt)
            if status == 200:
                p.breaker.record_success()
                self.timeline.observe("fleet.wire_bytes", len(payload))
                ctype = (hdrs.get("content-type") or "").lower()
                t_dec = time.perf_counter()
                if ctype.startswith(WIRE_CTYPE):
                    header, data = decode_product_wire(
                        payload, encoding=hdrs.get("content-encoding"))
                    self.timeline.count("fleet.wire.binary")
                else:
                    header, data = decode_product(json.loads(payload))
                    self.timeline.count("fleet.wire.json")
                self.timeline.observe("fleet.deserialize_s",
                                      time.perf_counter() - t_dec)
                return header, data, hdrs.get(TIER_HEADER.lower())
            try:
                body = json.loads(payload)
            except ValueError:
                body = payload.decode("utf-8", "replace")
            msg = (body.get("error") if isinstance(body, dict)
                   else str(body)[:200])
            if status == 503:
                raise Overloaded(
                    f"peer {p.name}: {msg}",
                    retry_after_s=retry_after_from(hdrs, body))
            if status == 504:
                raise DeadlineExpired(f"peer {p.name}: {msg}")
            if status == 404:
                # A catalog miss (ISSUE 19): the CALLER named a
                # session/scan the archive does not hold — terminal and
                # breaker-neutral, never a host failure.
                from blit.serve.catalog import CatalogMiss

                p.breaker.record_success()
                raise CatalogMiss(f"peer {p.name}: {msg}")
            raise PeerHTTPError(
                f"peer {p.name} answered HTTP {status}: {msg}")

    # -- cache-warm replication --------------------------------------------
    def _note_hot(self, fp: str, recipe: Dict) -> None:
        with self._lock:
            hits, _ = self._hot.get(fp, (0, None))
            hits += 1
            self._hot[fp] = (hits, recipe)
            self._hot.move_to_end(fp)
            while len(self._hot) > self._hot_max:
                self._hot.popitem(last=False)
        if hits != self.hot_hits:
            return
        # Crossing the hotness threshold: warm the REPLICAS now, so
        # losing the owner later degrades hit-rate, not correctness —
        # and the degradation recovers from a warm disk tier, not a
        # recompute storm.
        replicas = self.ring.owners(fp)[1:]
        if replicas:
            self.timeline.count("fleet.warm")
            threading.Thread(
                target=self._send_warm,
                args=([self._peers[n] for n in replicas], [recipe],
                      observability.tracer().context()),
                name="blit-fleet-warm", daemon=True).start()

    def warm_hints(self, in_range=None, limit: int = 32
                   ) -> List[Tuple[str, Dict]]:
        """The hottest ``(fp, recipe)`` pairs the door knows, hottest
        first, restricted to the fingerprints ``in_range`` accepts (a
        predicate; None = all) — the drain-time hint source (ISSUE 14),
        range-scoped for elastic warm handoff (ISSUE 17) so a joiner is
        streamed exactly its incoming key range."""
        with self._lock:
            items = sorted(self._hot.items(), key=lambda kv: kv[1][0],
                           reverse=True)
        out: List[Tuple[str, Dict]] = []
        for fp, (_, recipe) in items:
            if recipe is None:
                continue
            if in_range is not None and not in_range(fp):
                continue
            out.append((fp, recipe))
            if len(out) >= max(0, int(limit)):
                break
        return out

    def _send_warm(self, peers: List[_Peer], recipes: List[Dict],
                   ctx: Optional[Dict] = None) -> None:
        # Warm hints carry the hot request's trace (ISSUE 15): the
        # replication work a request triggers stays attributable to it.
        tr = observability.tracer()
        with tr.activate(ctx), tr.span("fleet.warm", peers=len(peers)):
            hdrs = trace_headers()
            for p in peers:
                try:
                    http_json("POST", p.url, "/warm",
                              {"recipes": recipes}, timeout=5.0,
                              headers=hdrs, pool=self.pool)
                except OSError:
                    pass  # warming is best-effort by definition

    # -- surfaces ----------------------------------------------------------
    def health(self) -> Dict:
        """The aggregated fleet ``/healthz`` (ISSUE 14 satellite): one
        probe answers "is the fleet serving" — the door's own state
        (draining, ejections, breakers) folded with every peer's last
        health document via :func:`blit.monitor.fold_health`."""
        from blit.monitor import fold_health

        own: List[str] = []
        with self._lock:
            if self._draining:
                own.append("draining")
            resizing = self.resize_reason
        standbys: List[str] = []
        peer_health: Dict[str, Optional[Dict]] = {}
        for name, p in sorted(self._peers.items()):
            if not p.in_ring:
                if p.standby:
                    standbys.append(name)  # capacity, not a casualty
                elif not p.retired:  # retired = deliberate scale-in
                    own.append(f"peer-ejected:{name}")
                continue
            state = p.breaker.snapshot()["state"]
            if state != "closed":
                own.append(f"breaker-{state.replace('-', '_')}:{name}")
            peer_health[name] = p.last_health
        if resizing:
            own.append(f"resizing:{resizing}")
        doc = fold_health(own, peer_health)
        doc["ring"] = self.ring.peers()
        doc["peers_total"] = len(self._peers)
        doc["standbys"] = standbys
        if resizing:
            # Honest mid-flip status (ISSUE 17 satellite): routing is
            # transiently degraded while membership flips — "ok" here
            # would lie to the probe that decides where traffic goes.
            doc["ok"] = False
            doc["status"] = "resizing"
        if not len(self.ring):
            doc["ok"] = False
            doc["status"] = "down"
        return doc

    def stats(self) -> Dict:
        with self._lock:
            hot = sorted(((fp, h) for fp, (h, _) in self._hot.items()),
                         key=lambda kv: kv[1], reverse=True)[:8]
            inflight = self._inflight
        rep = self.timeline.report()
        counters = {k: row["calls"] for k, row in rep.items()
                    if k.startswith(("fleet.", "elastic.", "catalog."))
                    and isinstance(row, dict) and "calls" in row}
        return {
            "catalog": (self.catalog.stats()
                        if self.catalog is not None else None),
            "peers": {n: p.snapshot()
                      for n, p in sorted(self._peers.items())},
            "ring": self.ring.peers(),
            "replicas": self.replicas,
            "wire": self.wire,
            "pool": self.pool.stats(),
            "inflight": inflight,
            "draining": self._draining,
            "hot": [[fp[:16], h] for fp, h in hot],
            "counters": counters,
            "hists": {k: v for k, v in (rep.get("hists") or {}).items()
                      if k in FLEET_HISTS},
        }

    def history(self, since: float, until: float, *,
                tier: Optional[str] = None) -> Dict:
        """Fleet-wide history range query (ISSUE 20): fan ``GET
        /history`` out to every in-ring peer and fold the answers with
        :func:`blit.history.merge_buckets` — the same commutative
        hist-state/stage/burn fold the stores use locally, so the
        merged series read exactly as one peer's would.  Peers that
        fail or answer without a store are skipped and named."""
        from blit.history import merge_buckets

        q = f"/history?since={since}&until={until}"
        if tier:
            q += f"&tier={tier}"
        lists: List[List[Dict]] = []
        answered: List[str] = []
        skipped: List[str] = []
        with self._lock:
            peers = [(n, p) for n, p in sorted(self._peers.items())
                     if p.in_ring]
        for name, p in peers:
            try:
                status, _, body = http_json("GET", p.url, q,
                                            timeout=10.0, pool=self.pool)
            except OSError:
                skipped.append(name)
                continue
            if status == 200 and isinstance(body, dict) \
                    and body.get("enabled"):
                lists.append(body.get("buckets") or [])
                answered.append(name)
            else:
                skipped.append(name)
        return {"t0": since, "t1": until, "peers": answered,
                "skipped": skipped,
                "buckets": merge_buckets(lists)}

    def metrics_prometheus(self, openmetrics: bool = False) -> str:
        snapshot = {"host": hostname(), "pid": os.getpid(), "worker": 0,
                    "timeline": self.timeline.state(),
                    "faults": faults.counters(), "spans": []}
        return render_prometheus(merge_fleet([snapshot]),
                                 openmetrics=openmetrics)

    # -- drain / teardown --------------------------------------------------
    def drain(self, timeout: Optional[float] = 30.0,
              hints: int = 32) -> Dict[str, int]:
        """Graceful front-door shutdown (tentpole #5): refuse new
        requests NOW, wait for in-flight ones to finish, then hand the
        ``hints`` hottest fingerprints' recipes to their current
        owner+replica peers as ``/warm`` hints — the door's working-set
        knowledge outlives the door."""
        with self._drain_cond:
            self._draining = True
            deadline = (None if timeout is None
                        else time.monotonic() + timeout)
            while self._inflight > 0:
                if deadline is not None and time.monotonic() >= deadline:
                    log.warning("fleet drain timed out with %d in-flight",
                                self._inflight)
                    break
                self._drain_cond.wait(timeout=0.1)
        per_peer: Dict[str, List[Dict]] = {}
        for fp, recipe in self.warm_hints(limit=hints):
            for name in self.ring.owners(fp):
                per_peer.setdefault(name, []).append(recipe)
        sent = 0
        for name, recipes in per_peer.items():
            try:
                http_json("POST", self._peers[name].url, "/warm",
                          {"recipes": recipes}, timeout=5.0,
                          pool=self.pool)
                sent += len(recipes)
            except OSError:
                pass
        self.timeline.count("fleet.drain.hints", sent)
        log.info("fleet drain: %d hot-entry hints handed to %d peer(s)",
                 sent, len(per_peer))
        return {"hints": sent, "peers_hinted": len(per_peer)}

    def close(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
            self._thread = None
        self.pool.close()
        if self.request_log is not None:
            self.request_log.close()

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.close()


def peers_from_spec(spec: Iterable[str]) -> Dict[str, str]:
    """Parse ``name=url`` (or bare ``url`` → ``peer<i>``) peer specs —
    the CLI's ``--peer`` flag grammar."""
    out: Dict[str, str] = {}
    for i, s in enumerate(spec):
        if "=" in s:
            name, url = s.split("=", 1)
        else:
            name, url = f"peer{i}", s
        out[name] = url.rstrip("/")
    return out


__all__ = ["FLEET_HISTS", "FleetError", "FleetFrontDoor",
           "PeerHTTPError", "peers_from_spec"]
