"""Stdlib-HTTP plumbing for the fleet serve plane (ISSUE 14 tentpole).

One wire, three speakers:

- **codecs** — :func:`wire_request` / :func:`request_from_wire` carry a
  :class:`~blit.serve.service.ProductRequest` as its JSON recipe (the
  ISSUE 13 re-derivation recipe made transport), and
  :func:`encode_product` / :func:`decode_product` carry the finished
  ``(header, array)`` product as JSON + base64 payload bytes — small
  products by design (the serve layer returns reduced arrays, not raw
  voltages), so JSON keeps every hop debuggable with ``curl``.  The
  hot path speaks ``application/x-blit-product`` instead (ISSUE 16):
  :func:`encode_product_wire` / :func:`decode_product_wire` frame the
  same product as a length-prefixed JSON meta document + the raw
  C-order payload bytes — no base64 size tax, no payload copy on
  decode — negotiated by ``Accept`` so legacy JSON clients keep
  working bit-for-bit (``X-Blit-Wire`` on the response says which
  form answered).
- **transport** — :func:`http_request` is the byte-exact transport
  half (one round-trip → status, headers, payload bytes);
  :func:`http_json` is the codec half layered on top.
  :class:`ConnectionPool` gives the fleet's hops bounded per-peer
  keep-alive sockets; transport errors on a reused socket evict it
  and retry once on a fresh dial, so the PR-13 failover/breaker
  semantics only ever judge fresh-dial verdicts.
- :class:`PeerServer` — one serving peer: a
  :class:`~blit.serve.service.ProductService` behind ``POST /product``
  (+ ``/warm`` cache-warm hints, ``/stats``, ``POST /drain``), with the
  ``/metrics``–``/healthz`` surface REUSED from
  :class:`blit.monitor.MetricsPublisher` (same Prometheus exposition,
  same honest-degradation health document) and a heartbeat
  :class:`blit.recover.Lease` beaten on a background thread so the
  front door detects a dead/wedged peer within the lease TTL — the
  recover-plane staleness contract applied to serving.
- :class:`FrontDoorServer` — the fleet front door
  (:class:`blit.serve.fleet.FleetFrontDoor`) as an HTTP service with
  the same ``/product`` shape, an AGGREGATED ``/healthz``
  (:func:`blit.monitor.fold_health`), and ``/metrics`` for the routing
  counters (hedges, failovers, ejections).

Error mapping, both servers: :class:`~blit.serve.scheduler.Overloaded`
→ **503** with the seeded-jitter ``retry_after_s`` honored as the
``Retry-After`` header (the thundering-herd satellite);
:class:`~blit.serve.scheduler.DeadlineExpired` → **504** (the request
was never computed); anything else → **500** carrying the error type.

:func:`install_drain_handler` wires SIGTERM/SIGINT to a graceful drain
(refuse new, finish in-flight, release ``kind="stream"`` holds) — used
by ``blit fleet-peer`` and ``blit serve-bench`` so an interpreter exit
stops leaking capacity holds (ISSUE 14 satellite).
"""

from __future__ import annotations

import base64
import json
import logging
import threading
import time
import zlib
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

from blit import faults, observability
from blit.config import DEFAULT, SiteConfig
from blit.serve.scheduler import DeadlineExpired, Overloaded

log = logging.getLogger("blit.serve.http")


# -- trace context on the wire (ISSUE 15 tentpole #1) ------------------------
#
# Every fleet HTTP hop carries the PR-5 trace context as headers, so the
# receiving process reactivates the caller's context and its spans
# parent onto the caller's — one request, one trace, across processes.
TRACE_HEADER = "X-Blit-Trace"
SPAN_HEADER = "X-Blit-Span"
HEDGE_HEADER = "X-Blit-Hedge"
REQUEST_ID_HEADER = "X-Blit-Request"
# Response side: the peer reports which cache tier answered, so the
# front door's access record carries the tier outcome it cannot see.
TIER_HEADER = "X-Blit-Tier"


def trace_headers(ctx: Optional[Dict] = None, *, hedge: bool = False,
                  rid: Optional[str] = None) -> Dict[str, str]:
    """The outgoing headers for one hop: the ambient (or given) trace
    context, the hedge tag, and the request id."""
    if ctx is None:
        ctx = observability.tracer().context()
    out: Dict[str, str] = {}
    if ctx:
        out[TRACE_HEADER] = str(ctx.get("trace", ""))
        out[SPAN_HEADER] = str(ctx.get("span", ""))
    if hedge:
        out[HEDGE_HEADER] = "1"
    if rid:
        out[REQUEST_ID_HEADER] = rid
    return out


def trace_context_from(headers: Optional[Dict]) -> Optional[Dict]:
    """The ``{"trace", "span"}`` context a request's headers carry
    (None when absent) — feed it to ``tracer().activate`` so peer-side
    spans parent onto the caller's span across the process boundary."""
    if not headers:
        return None
    trace = headers.get(TRACE_HEADER.lower())
    span = headers.get(SPAN_HEADER.lower())
    if not trace or not span:
        return None
    return {"trace": trace, "span": span}


# -- wire codecs -------------------------------------------------------------


def encode_product(header: Dict, data: np.ndarray) -> Dict:
    """The JSON wire form of a finished product: header + shape/dtype +
    base64 payload bytes (C-order)."""
    arr = np.ascontiguousarray(data)
    return {
        "header": {k: (v.item() if isinstance(v, np.generic) else v)
                   for k, v in dict(header).items()},
        "shape": list(arr.shape),
        "dtype": str(arr.dtype),
        "data_b64": base64.b64encode(arr.tobytes()).decode("ascii"),
    }


def decode_product(doc: Dict) -> Tuple[Dict, np.ndarray]:
    """Inverse of :func:`encode_product` — the array comes back
    READ-ONLY (``np.frombuffer`` of immutable bytes), matching the
    cache's frozen-result contract."""
    raw = base64.b64decode(doc["data_b64"])
    arr = np.frombuffer(raw, dtype=np.dtype(doc["dtype"]))
    arr = arr.reshape(tuple(doc["shape"]))
    return dict(doc["header"]), arr


# -- binary product wire (ISSUE 16 tentpole #1) ------------------------------
#
# ``application/x-blit-product``: WIRE_MAGIC, a big-endian u32 meta
# length, the JSON meta document ({"header", "shape", "dtype", "order"}
# — dtype as numpy's ``.str`` form, e.g. "<f4", so endianness rides the
# wire explicitly), then the raw C-order payload bytes.  Compared with
# the JSON+base64 wire: no ~33% base64 size tax, no encode copy on a
# cached hit (the frame is the cacheable body), and decode is an
# ``np.frombuffer`` view over the received buffer — zero payload
# copies on either end.

WIRE_CTYPE = "application/x-blit-product"
WIRE_HEADER = "X-Blit-Wire"
WIRE_MAGIC = b"BLW1"
# A product meta document is a header + shape/dtype — kilobytes.  A
# frame claiming more is torn or hostile: refuse before allocating.
WIRE_MAX_META = 4 << 20


class WireError(ValueError):
    """A binary product frame that cannot be trusted: bad magic, a
    truncated meta/payload, or an implausible meta length."""


def encode_product_parts(header: Dict,
                         data: np.ndarray) -> Tuple[bytes, memoryview]:
    """The zero-copy form of :func:`encode_product_wire`:
    ``(prefix bytes, payload buffer)`` with the payload a flat byte
    memoryview of the (contiguous) array — the server writes both
    straight to the socket without joining them into one copy."""
    arr = np.ascontiguousarray(data)
    meta = json.dumps({
        "header": {k: (v.item() if isinstance(v, np.generic) else v)
                   for k, v in dict(header).items()},
        "shape": list(arr.shape),
        "dtype": arr.dtype.str,
        "order": "C",
    }).encode()
    if len(meta) > WIRE_MAX_META:
        raise WireError(f"product meta is {len(meta)} bytes "
                        f"(cap {WIRE_MAX_META})")
    prefix = WIRE_MAGIC + len(meta).to_bytes(4, "big") + meta
    # memoryview.cast refuses zero-size shapes; an empty product's
    # payload is simply no bytes.
    payload = (memoryview(b"") if arr.size == 0
               else memoryview(arr).cast("B"))
    return prefix, payload


def encode_product_wire(header: Dict, data: np.ndarray, *,
                        deflate: bool = False) -> bytes:
    """One ``application/x-blit-product`` frame as bytes — the
    cacheable wire body (ISSUE 16 tentpole #3).  ``deflate``
    zlib-compresses the WHOLE frame; the response then carries
    ``Content-Encoding: deflate`` (worth it for compressible products
    only — float spectra mostly are not, so it defaults off)."""
    prefix, payload = encode_product_parts(header, data)
    body = prefix + bytes(payload)
    if deflate:
        body = zlib.compress(body, 6)
    return body


def decode_product_wire(buf, *,
                        encoding: Optional[str] = None
                        ) -> Tuple[Dict, np.ndarray]:
    """Inverse of :func:`encode_product_wire` — the array is a
    READ-ONLY ``np.frombuffer`` view over ``buf``'s payload bytes (the
    frozen-result contract, with zero payload copies).  Raises
    :class:`WireError` on a frame that cannot be trusted."""
    if encoding:
        if encoding.strip().lower() != "deflate":
            raise WireError(f"unknown content encoding {encoding!r}")
        try:
            buf = zlib.decompress(bytes(buf))
        except zlib.error as e:
            raise WireError(f"undecodable deflate frame: {e}") from None
    view = memoryview(buf)
    if len(view) < 8 or bytes(view[:4]) != WIRE_MAGIC:
        raise WireError("not a blit product frame (bad magic)")
    n = int.from_bytes(view[4:8], "big")
    if n > WIRE_MAX_META:
        raise WireError(f"implausible meta length {n} "
                        f"(cap {WIRE_MAX_META})")
    if len(view) < 8 + n:
        raise WireError(f"truncated frame: meta claims {n} bytes, "
                        f"{max(0, len(view) - 8)} present")
    try:
        meta = json.loads(bytes(view[8:8 + n]))
        dtype = np.dtype(meta["dtype"])
        shape = tuple(int(s) for s in meta["shape"])
    except (ValueError, KeyError, TypeError) as e:
        raise WireError(f"unparseable frame meta: {e}") from None
    want = dtype.itemsize * int(np.prod(shape, dtype=np.int64))
    payload = view[8 + n:]
    if payload.nbytes != want:
        raise WireError(f"truncated frame: payload is {payload.nbytes} "
                        f"bytes, {dtype}{shape} needs {want}")
    arr = np.frombuffer(payload, dtype=dtype).reshape(shape)
    arr.setflags(write=False)
    return dict(meta["header"]), arr


def wants_binary_product(accept: Optional[str]) -> bool:
    """Did the request's ``Accept`` header ask for the binary product
    wire?  Absent/other → the legacy JSON wire, bit-for-bit."""
    return WIRE_CTYPE in (accept or "")


def wants_deflate(accept_encoding: Optional[str]) -> bool:
    return "deflate" in (accept_encoding or "")


def wire_request(request, *, priority: int = 1, client: str = "anon",
                 deadline_s: Optional[float] = None) -> Dict:
    """A :class:`~blit.serve.service.ProductRequest` as one wire
    document.  Live sessions (``kind="stream"``) are refused: a session
    is pinned to ONE host for its recording's duration — it has no
    meaningful ring owner, no replica, and no cacheable result, so the
    fleet plane serves bounded products only."""
    if request.kind == "stream":
        raise ValueError(
            "kind='stream' live sessions do not ride the fleet wire — "
            "submit them to one peer's ProductService directly")
    return {"recipe": request.recipe(), "priority": int(priority),
            "client": str(client),
            "deadline_s": (None if deadline_s is None
                           else float(deadline_s))}


def request_from_wire(doc: Dict):
    """``(ProductRequest, priority, client, deadline_s)`` from a wire
    document (unknown recipe keys ignored — the
    :meth:`ProductRequest.from_recipe` forward-compat rule)."""
    from blit.serve.service import ProductRequest

    req = ProductRequest.from_recipe(doc["recipe"])
    return (req, int(doc.get("priority", 1)),
            str(doc.get("client", "anon")), doc.get("deadline_s"))


# -- tiny HTTP client --------------------------------------------------------


class ConnectionPool:
    """A bounded, thread-safe per-peer keep-alive pool (ISSUE 16
    tentpole #2) replacing the per-call ``HTTPConnection``:
    :meth:`request` leases a pooled socket to the target host (LIFO —
    the warmest socket first), runs one round-trip, and returns the
    socket when the response allows reuse.  A transport error on a
    REUSED socket evicts it and retries ONCE on a fresh dial — safe
    because every fleet POST is idempotent (content-addressed
    products, best-effort warms) — so breakers and failover only ever
    judge fresh-dial verdicts, exactly as before pooling.
    ``fleet.pool.open`` / ``fleet.pool.reuse`` / ``fleet.pool.evict``
    ride ``timeline``; the ``pool.reuse`` fault point fires on the
    reused-socket leg only (the ``BLIT_FAULTS`` drill seam —
    :class:`~blit.faults.InjectedFault` is an ``OSError``, so a bare
    injected fault IS a mid-flight reset)."""

    def __init__(self, max_per_peer: int = 4, timeline=None):
        self.max_per_peer = max(1, int(max_per_peer))
        self.timeline = timeline
        self._lock = threading.Lock()
        self._idle: Dict[Tuple[str, int], List] = {}
        self._closed = False

    def _count(self, name: str) -> None:
        if self.timeline is not None:
            self.timeline.count(name)

    def _take(self, key):
        with self._lock:
            conns = self._idle.get(key)
            if conns:
                return conns.pop()
        return None

    def _give(self, key, conn) -> None:
        with self._lock:
            if not self._closed:
                conns = self._idle.setdefault(key, [])
                if len(conns) < self.max_per_peer:
                    conns.append(conn)
                    return
        conn.close()

    def stats(self) -> Dict[str, int]:
        """Idle sockets per peer — the reuse-ratio denominator lives
        on the timeline counters; this is the live pool occupancy."""
        with self._lock:
            return {f"{h}:{p}": len(c)
                    for (h, p), c in self._idle.items() if c}

    def evict_peer(self, url: str) -> int:
        """Sever and drop every idle socket to ``url``'s host — called
        when a peer leaves the ring (ejection or elastic scale-in,
        ISSUE 17) so no later request is written to a departed peer's
        dead keep-alive.  Counts ``fleet.pool.evict`` per socket;
        returns how many were evicted."""
        from urllib.parse import urlsplit

        parts = urlsplit(url)
        key = (parts.hostname or "127.0.0.1", parts.port or 80)
        with self._lock:
            conns = self._idle.pop(key, [])
        for c in conns:
            self._count("fleet.pool.evict")
            try:
                c.close()
            except OSError:
                pass
        return len(conns)

    def close(self) -> None:
        with self._lock:
            self._closed = True
            conns = [c for lst in self._idle.values() for c in lst]
            self._idle.clear()
        for c in conns:
            try:
                c.close()
            except OSError:
                pass

    def request(self, method: str, url: str, path: str, body=None,
                headers: Optional[Dict[str, str]] = None,
                timeout: float = 10.0
                ) -> Tuple[int, Dict[str, str], bytes]:
        """One round-trip → ``(status, lower-cased headers, payload
        bytes)``.  Raises ``OSError`` on fresh-dial transport failure,
        exactly like an unpooled connection — a reused-socket failure
        is absorbed by the evict-and-redial retry."""
        import http.client
        from urllib.parse import urlsplit

        parts = urlsplit(url)
        key = (parts.hostname or "127.0.0.1", parts.port or 80)
        conn = self._take(key)
        if conn is not None:
            self._count("fleet.pool.reuse")
            try:
                faults.fire("pool.reuse", key=f"{key[0]}:{key[1]}")
                return self._roundtrip(conn, key, method, path, body,
                                       headers, timeout)
            except OSError:
                # Stale keep-alive (peer restarted, idle timeout,
                # mid-flight reset): evict, fall through to the dial.
                self._count("fleet.pool.evict")
                try:
                    conn.close()
                except OSError:
                    pass
        conn = http.client.HTTPConnection(key[0], key[1], timeout=timeout)
        self._count("fleet.pool.open")
        try:
            return self._roundtrip(conn, key, method, path, body,
                                   headers, timeout)
        except BaseException:
            conn.close()
            raise

    def _roundtrip(self, conn, key, method, path, body, headers,
                   timeout) -> Tuple[int, Dict[str, str], bytes]:
        # Per-request deadline on a long-lived socket: the connection's
        # dial timeout is whatever the FIRST request chose — retune it.
        conn.timeout = timeout
        if conn.sock is not None:
            conn.sock.settimeout(timeout)
        conn.request(method, path, body=body, headers=dict(headers or {}))
        resp = conn.getresponse()
        payload = resp.read()
        hdrs = {k.lower(): v for k, v in resp.getheaders()}
        if resp.will_close:
            conn.close()
        else:
            self._give(key, conn)
        return resp.status, hdrs, payload


def http_request(method: str, url: str, path: str, body=None,
                 headers: Optional[Dict[str, str]] = None,
                 timeout: float = 10.0,
                 pool: Optional[ConnectionPool] = None,
                 ) -> Tuple[int, Dict[str, str], bytes]:
    """One HTTP round-trip to ``url`` (``http://host:port``) →
    ``(status, lower-cased headers, payload bytes)`` — the byte-exact
    TRANSPORT half of :func:`http_json` (ISSUE 16 satellite: binary
    bodies round-trip untouched, no lossy text decode).  ``pool``
    reuses a :class:`ConnectionPool` keep-alive socket; without one
    the connection is dialed and closed per call.  Raises ``OSError``
    on transport failure (refused/reset/timeout), which the front
    door classifies as a peer failure."""
    if pool is not None:
        return pool.request(method, url, path, body=body,
                            headers=headers, timeout=timeout)
    import http.client
    from urllib.parse import urlsplit

    parts = urlsplit(url)
    conn = http.client.HTTPConnection(parts.hostname,
                                      parts.port or 80, timeout=timeout)
    try:
        conn.request(method, path, body=body, headers=dict(headers or {}))
        resp = conn.getresponse()
        payload = resp.read()
        hdrs = {k.lower(): v for k, v in resp.getheaders()}
        return resp.status, hdrs, payload
    finally:
        conn.close()


def http_json(method: str, url: str, path: str, doc: Optional[Dict] = None,
              timeout: float = 10.0,
              headers: Optional[Dict[str, str]] = None,
              pool: Optional[ConnectionPool] = None,
              ) -> Tuple[int, Dict[str, str], object]:
    """One JSON request to ``url`` (``http://host:port``) →
    ``(status, headers, body)`` — the body is the parsed JSON when the
    response says so, the raw BYTES for a binary content type (the
    product wire — never text-decoded), else decoded text
    (``/metrics``).  ``headers`` adds extra request headers (the
    trace-context hop); ``pool`` rides a keep-alive socket.  Raises
    ``OSError`` on transport failure (refused/reset/timeout), which
    the front door classifies as a peer failure."""
    req_hdrs = dict(headers or {})
    body = None
    if doc is not None:
        body = json.dumps(doc).encode()
        req_hdrs["Content-Type"] = "application/json"
    status, hdrs, payload = http_request(method, url, path, body=body,
                                         headers=req_hdrs,
                                         timeout=timeout, pool=pool)
    ctype = (hdrs.get("content-type") or "").lower()
    if "json" in ctype:
        try:
            return status, hdrs, json.loads(payload or b"{}")
        except ValueError:
            pass
    if ctype.startswith(WIRE_CTYPE) or ctype.startswith(
            "application/octet"):
        return status, hdrs, payload
    return status, hdrs, payload.decode("utf-8", "replace")


# -- shared server skeleton --------------------------------------------------


def _make_server(router: Callable, port: int, host: str = "127.0.0.1"):
    """A ThreadingHTTPServer whose GET/POST route through ``router``:
    ``router(method, path, doc, headers) -> (status, body, ctype,
    headers)`` — the :func:`blit.monitor._make_http_server` shape,
    generalized so the peer and the front door share one handler.
    ``headers`` is the request's header map with lower-cased keys (the
    trace-context hop rides it).  ``host`` defaults to loopback (safe
    local default); a multi-host fleet binds ``"0.0.0.0"``
    (``blit fleet-peer --host``)."""
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    class Handler(BaseHTTPRequestHandler):
        # HTTP/1.1 keep-alive (ISSUE 16): the fleet's ConnectionPool
        # reuses sockets across requests.  Safe with the stdlib
        # handler because every response carries Content-Length.
        protocol_version = "HTTP/1.1"

        def _route(self, method: str):
            try:
                doc = None
                n = int(self.headers.get("Content-Length") or 0)
                if n:
                    try:
                        doc = json.loads(self.rfile.read(n))
                    except ValueError:
                        self.send_error(400, "unparseable JSON body")
                        return
                hdrs = {k.lower(): v for k, v in self.headers.items()}
                status, body, ctype, extra = router(
                    method, self.path, doc, hdrs)
            except Exception as e:  # noqa: BLE001 — a request must not kill
                log.warning("http route failed", exc_info=True)
                status, body, ctype, extra = (
                    500, json.dumps({"error": str(e),
                                     "etype": type(e).__name__}),
                    "application/json", {})
            if isinstance(body, tuple):
                # Zero-copy wire body (ISSUE 16): (prefix bytes,
                # payload buffer) written straight through — the
                # product's bytes are never joined into one copy.
                parts = list(body)
            else:
                parts = [body.encode() if isinstance(body, str) else body]
            self.send_response(status)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length",
                             str(sum(len(p) for p in parts)))
            for k, v in (extra or {}).items():
                self.send_header(k, str(v))
            self.end_headers()
            for p in parts:
                self.wfile.write(p)

        def do_GET(self):  # noqa: N802 — stdlib contract
            self._route("GET")

        def do_POST(self):  # noqa: N802 — stdlib contract
            self._route("POST")

        def log_message(self, fmt, *args):  # quiet request traffic
            log.debug("http: " + fmt, *args)

    class Server(ThreadingHTTPServer):
        """Tracks live connections so ``close_all_connections`` can
        sever keep-alive sockets: with HTTP/1.1, closing the listener
        alone would leave a "dead" server still answering pooled
        clients through established connections."""

        daemon_threads = True

        def __init__(self, *a, **kw):
            self._conns = set()
            self._conns_lock = threading.Lock()
            super().__init__(*a, **kw)

        def get_request(self):
            sock, addr = super().get_request()
            with self._conns_lock:
                self._conns.add(sock)
            return sock, addr

        def shutdown_request(self, request):
            with self._conns_lock:
                self._conns.discard(request)
            super().shutdown_request(request)

        def close_all_connections(self):
            import socket as _socket

            with self._conns_lock:
                conns = list(self._conns)
                self._conns.clear()
            for s in conns:
                try:
                    s.shutdown(_socket.SHUT_RDWR)
                except OSError:
                    pass
                try:
                    s.close()
                except OSError:
                    pass

    return Server((host, int(port)), Handler)


def _json_resp(status: int, doc: Dict,
               headers: Optional[Dict] = None) -> Tuple:
    return status, json.dumps(doc), "application/json", headers or {}


def history_query(path: str) -> Tuple[float, float, Optional[str]]:
    """Parse a ``GET /history`` query string → ``(since, until, tier)``
    epochs.  ``since``/``until`` accept the shared window grammar
    (:func:`blit.history.parse_when`: epoch, ``"15m"``-style
    ago-windows, ``"now"``); default: the last hour."""
    from urllib.parse import parse_qs, urlsplit

    from blit.history import parse_when

    q = parse_qs(urlsplit(path).query)
    now = time.time()
    until = parse_when(q["until"][0], now) if q.get("until") else now
    since = (parse_when(q["since"][0], now) if q.get("since")
             else until - 3600.0)
    tier = q["tier"][0] if q.get("tier") else None
    return since, until, tier


def _history_doc(pub, path: str) -> Dict:
    """The peer ``GET /history`` body: this process's bucket records
    over the queried window, in the fleet-merge wire shape (the door
    folds peers' answers with :func:`blit.history.merge_buckets`)."""
    since, until, tier = history_query(path)
    store = getattr(pub, "history", None)
    doc = {"t0": since, "t1": until, "enabled": store is not None,
           "host": observability.hostname(), "buckets": [], "metrics": []}
    if store is not None:
        doc["buckets"] = store.buckets(since, until, tier=tier)
        doc["metrics"] = store.metrics(window_s=max(60.0, until - since))
    return doc


def snapshot_with(timeline, name: Optional[str] = None) -> Dict:
    """This process's telemetry-snapshot wire document WITH spans — the
    ``/snapshot`` body both the peer and the front door serve
    (ISSUE 15 tentpole #4): the process timeline merged with the
    serving component's (histogram exemplars ride the state), plus the
    full span buffer, stitchable by ``blit trace-view --fleet``."""
    from blit.observability import Timeline, telemetry_snapshot

    doc = telemetry_snapshot(spans=True)
    merged = Timeline.from_state(doc["timeline"])
    merged.merge(timeline)
    doc["timeline"] = merged.state()
    if name is not None:
        doc["name"] = name
    return doc


def _error_resp(e: BaseException) -> Tuple:
    """The shared serve-error → HTTP mapping (module docstring)."""
    if isinstance(e, DeadlineExpired):
        return _json_resp(504, {"error": str(e), "etype": "DeadlineExpired",
                                "retry_after_s": e.retry_after_s})
    if isinstance(e, Overloaded):
        # The jittered back-off hint honored ON THE WIRE (ISSUE 14
        # satellite): every rejected client reads a DIFFERENT
        # Retry-After, so the herd does not return in one instant.
        ra = max(0.0, float(e.retry_after_s))
        return _json_resp(503, {"error": str(e), "etype": "Overloaded",
                                "retry_after_s": ra},
                          {"Retry-After": f"{ra:.3f}"})
    if type(e).__name__ == "CatalogMiss":
        # An archive session/scan the catalog does not hold (ISSUE 19)
        # — the caller named it wrong: not-found, breaker-neutral.
        return _json_resp(404, {"error": str(e), "etype": "CatalogMiss"})
    return _json_resp(500, {"error": str(e), "etype": type(e).__name__})


# -- the serving peer --------------------------------------------------------


class PeerServer:
    """One cache/compute peer of the fleet (module docstring): a
    :class:`~blit.serve.service.ProductService` served over HTTP, with
    lease heartbeats and the monitor plane's ``/metrics``–``/healthz``
    surface.  ``port=0`` binds an ephemeral port (``.port`` / ``.url``
    say which).  ``lease_dir``/``proc`` arm the heartbeat lease the
    front door watches; ``beat_interval_s`` should sit well under the
    fleet's ``peer_ttl_s`` (default: 0.5 s).

    The server owns its HTTP lifecycle but NOT the service: ``close()``
    stops serving and beating; draining/closing the service stays the
    caller's call (``blit fleet-peer`` wires SIGTERM → :meth:`drain` →
    exit)."""

    def __init__(self, service, *, name: str = "peer", port: int = 0,
                 host: str = "127.0.0.1",
                 lease_dir: Optional[str] = None, proc: int = 0,
                 beat_interval_s: float = 0.5,
                 request_timeout_s: float = 300.0,
                 config: SiteConfig = DEFAULT):
        self.service = service
        self.name = name
        self.request_timeout_s = float(request_timeout_s)
        # Whole-frame deflate on the binary wire, only when BOTH the
        # client advertises it and the knob says so (off by default:
        # float spectra compress poorly and the CPU tax lands on the
        # hot path).
        from blit.config import fleet_defaults

        self._wire_deflate = bool(fleet_defaults(config)["wire_deflate"])
        # Per-request access records (ISSUE 15 tentpole #2): one line
        # per handled /product with trace id, tier outcome, queue wait
        # and status — None (one attribute test per request) unless
        # BLIT_REQUEST_LOG / SiteConfig.request_log_dir is set.
        self.request_log = observability.request_log_for(
            f"peer-{name}", config)
        # The monitor plane's surface, reused wholesale: health() folds
        # breakers/recover-hooks/SLO burn; fleet_report() renders the
        # service timeline as native-histogram Prometheus exposition.
        from blit.monitor import MetricsPublisher

        # port=-1 / spool_dir="": explicitly OFF — this server IS the
        # peer's endpoint; the publisher only renders its bodies.  With
        # the history plane armed (BLIT_HISTORY_DIR), the publisher DOES
        # tick on the monitor interval so the peer's /history rings fill
        # and its anomaly baselines score (ISSUE 20) — still no second
        # HTTP endpoint and no spool.
        from blit.config import history_defaults, monitor_defaults

        history_on = bool(history_defaults(config)["enabled"])
        self._pub = MetricsPublisher(
            interval_s=(monitor_defaults(config)["interval_s"]
                        if history_on else 3600.0),
            spool_dir="", port=-1, timeline=service.timeline,
            config=config)
        if history_on:
            self._pub.start()
        self._server = _make_server(self._route, port, host)
        self.port = self._server.server_address[1]
        # The advertised URL: loopback when bound there, else the
        # wildcard bind resolves to this host's name for the peers map.
        adv = "127.0.0.1" if host in ("127.0.0.1", "localhost") else host
        self.url = f"http://{adv}:{self.port}"
        self._server_thread: Optional[threading.Thread] = None
        self._lease = None
        self._beat_stop = threading.Event()
        self._beat_thread: Optional[threading.Thread] = None
        if lease_dir is not None:
            from blit.recover import Lease

            self._lease = Lease(lease_dir, proc)
            self._beat_interval_s = max(0.05, float(beat_interval_s))
        self.counts: Dict[str, int] = {"product": 0, "warm": 0}
        self._counts_lock = threading.Lock()

    # -- routing -----------------------------------------------------------
    def _route(self, method: str, path: str, doc: Optional[Dict],
               headers: Optional[Dict] = None) -> Tuple:
        if method == "GET" and path.startswith("/healthz"):
            return _json_resp(200, self.health())
        if method == "GET" and path.startswith("/metrics"):
            from blit.observability import (
                OPENMETRICS_CTYPE,
                PROM_CTYPE,
                render_prometheus,
                wants_openmetrics,
            )

            om = wants_openmetrics((headers or {}).get("accept"))
            return (200, render_prometheus(self._pub.fleet_report(),
                                           openmetrics=om),
                    OPENMETRICS_CTYPE if om else PROM_CTYPE, {})
        if method == "GET" and path.startswith("/stats"):
            return _json_resp(200, self.stats())
        if method == "GET" and path.startswith("/snapshot"):
            # The fleet trace harvest surface (ISSUE 15 tentpole #4):
            # this process's span batch + merged timeline state (with
            # histogram exemplars), in the telemetry-snapshot wire
            # shape — `blit trace-view --fleet <url>` stitches these.
            return _json_resp(200, self.snapshot())
        if method == "GET" and path.startswith("/history"):
            # The durable-store range query (ISSUE 20): bucket records
            # over ?since/?until — empty (enabled=false) until
            # BLIT_HISTORY_DIR arms the plane.
            return _json_resp(200, _history_doc(self._pub, path))
        if method == "POST" and path.startswith("/product"):
            return self._handle_product(doc or {}, headers or {})
        if method == "POST" and path.startswith("/warm"):
            return self._handle_warm(doc or {}, headers or {})
        if method == "POST" and path.startswith("/drain"):
            threading.Thread(target=self.drain, name=f"{self.name}-drain",
                             daemon=True).start()
            return _json_resp(200, {"draining": True})
        return _json_resp(404, {"error": f"no route {method} {path}"})

    def _wire_resp(self, body: bytes, tier: Optional[str], rid: str,
                   deflate: bool) -> Tuple:
        """One binary-wire 200: the already-encoded frame (optionally
        whole-frame deflated), ``X-Blit-Wire: binary`` naming the
        negotiated form, and the tier/rid headers as on the JSON
        wire."""
        extra = {TIER_HEADER: tier, REQUEST_ID_HEADER: rid,
                 WIRE_HEADER: "binary"}
        if deflate:
            body = zlib.compress(body, 6)
            extra["Content-Encoding"] = "deflate"
        self.service.timeline.count("serve.wire.binary")
        self.service.timeline.observe("fleet.wire_bytes", len(body))
        return 200, body, WIRE_CTYPE, extra

    def _handle_product(self, doc: Dict, headers: Dict) -> Tuple:
        with self._counts_lock:
            self.counts["product"] += 1
        # Reactivate the caller's trace context (ISSUE 15 tentpole #1):
        # everything this request does on the peer — serve.reduce on the
        # scheduler's job thread included, via the submit-time context
        # capture — parents onto the FRONT DOOR's dispatch span, so one
        # request is one trace across processes.
        ctx = trace_context_from(headers)
        hedge = headers.get(HEDGE_HEADER.lower()) == "1"
        rid = headers.get(REQUEST_ID_HEADER.lower()) or observability.new_id()
        binary = wants_binary_product(headers.get("accept"))
        deflate = (binary and self._wire_deflate
                   and wants_deflate(headers.get("accept-encoding")))
        tr = observability.tracer()
        t0 = time.perf_counter()
        status, code, ticket, nbytes = "error", 500, None, 0
        fp = tier = qwait = None
        priority = client = deadline_s = None
        try:
            with tr.activate(ctx):
                req, priority, client, deadline_s = request_from_wire(doc)
                # The chaos schedule's injection point: kill/hang/delay
                # THIS peer on the Nth handled request (chaos --fleet).
                faults.fire("peer.request", key=str(req.raw_source))
                if binary:
                    # The encoded-body fast path (ISSUE 16 tentpole
                    # #3): a retained wire body answers without
                    # re-encoding — or even materializing — the array.
                    hit = self.service.wire_for(req)
                    if hit is not None:
                        fp, body, tier = hit
                        nbytes = len(body)
                        status, code = "ok", 200
                        return self._wire_resp(body, tier, rid, deflate)
                timeout = (min(self.request_timeout_s, deadline_s)
                           if deadline_s is not None
                           else self.request_timeout_s)
                # submit + result (not service.get): the ticket carries
                # the tier outcome and queue wait the access record —
                # and the front door, via the tier response header —
                # need.
                ticket = self.service.submit(
                    req, priority=priority, client=client,
                    deadline_s=deadline_s)
                try:
                    header, data = self.service.result(ticket,
                                                       timeout=timeout)
                except TimeoutError as e:
                    if deadline_s is None:
                        raise
                    # The reduction ran PAST the caller's deadline (the
                    # admission estimate under-predicted): that is a
                    # deadline verdict — 504, which the front door
                    # treats as breaker-NEUTRAL — not a peer failure
                    # that should trip a healthy host's breaker.
                    raise DeadlineExpired(
                        f"deadline {deadline_s:.3f}s expired "
                        f"mid-compute: {e}") from e
            nbytes = data.nbytes
            status, code = "ok", 200
            fp, tier = ticket.fingerprint, ticket.source
            qwait = round(ticket.queue_wait_s(), 6)
            if binary:
                t_enc = time.perf_counter()
                body = encode_product_wire(header, data)
                self.service.timeline.observe(
                    "fleet.serialize_s", time.perf_counter() - t_enc)
                # Retain the encoded body: the NEXT binary hit for
                # this fingerprint skips the encode entirely.  Catalog
                # documents regenerate per ask (the tree grows under
                # them) — never retained (ISSUE 19).
                if tier != "catalog":
                    self.service.cache.put_wire(fp, body)
                return self._wire_resp(body, tier, rid, deflate)
            t_enc = time.perf_counter()
            resp = _json_resp(200, encode_product(header, data),
                              {TIER_HEADER: tier,
                               REQUEST_ID_HEADER: rid,
                               WIRE_HEADER: "json"})
            self.service.timeline.observe(
                "fleet.serialize_s", time.perf_counter() - t_enc)
            self.service.timeline.count("serve.wire.json")
            self.service.timeline.observe("fleet.wire_bytes",
                                          len(resp[1]))
            return resp
        except BaseException as e:  # noqa: BLE001 — mapped onto the wire
            from blit.serve.scheduler import classify_failure

            resp = _error_resp(e)
            status, _ = classify_failure(e)
            # The record's code is WIRE truth — what this handler
            # actually answered (matches classify_failure except the
            # bare-TimeoutError corner, where the wire says 500).
            code = resp[0]
            return resp
        finally:
            if self.request_log is not None:
                dt = time.perf_counter() - t0
                if fp is None and ticket is not None:
                    # A failed flight still records its routing truth.
                    fp, tier = ticket.fingerprint, ticket.source
                    qwait = round(ticket.queue_wait_s(), 6)
                self.request_log.record(
                    rid=rid, trace=(ctx or {}).get("trace"), role="peer",
                    peer=self.name, client=client, priority=priority,
                    fp=(fp[:16] if fp else None),
                    tier=tier, queue_wait_s=qwait,
                    deadline_s=deadline_s,
                    deadline_left_s=(round(deadline_s - dt, 6)
                                     if deadline_s is not None else None),
                    hedged=(1 if hedge else None), status=status,
                    code=code, bytes=nbytes, duration_s=round(dt, 6))

    def _handle_warm(self, doc: Dict, headers: Dict) -> Tuple:
        """Cache-warm hints (ISSUE 14): submit each recipe at the
        lowest priority, fire-and-forget — a warm failure is a cold
        cache, never an error.  The peer's own cache/single-flight
        machinery dedupes repeats.  Warm reductions parent onto the
        hinting door's trace (ISSUE 15) so replication work is
        attributable to the request that made the entry hot.

        Elastic warm handoff (ISSUE 17) sends ``wait_s``: the response
        then blocks until the accepted recipes complete (or the budget
        burns), answering ``completed`` / ``bytes`` / ``timed_out`` —
        the joiner's warm-completion ack the controller gates the
        membership flip on.  ``priority`` overrides the default 9 so a
        handoff outranks background replication."""
        accepted = rejected = 0
        tickets: List = []
        from blit.serve.service import ProductRequest

        try:
            priority = int(doc.get("priority", 9))
        except (TypeError, ValueError):
            priority = 9
        tr = observability.tracer()
        with tr.activate(trace_context_from(headers)):
            for recipe in (doc.get("recipes") or []):
                with self._counts_lock:
                    self.counts["warm"] += 1
                try:
                    tickets.append(self.service.submit(
                        ProductRequest.from_recipe(recipe),
                        priority=priority, client="fleet-warm"))
                    accepted += 1
                except Exception:  # noqa: BLE001 — warming is best-effort
                    rejected += 1
        self.service.timeline.count("serve.warm", accepted)
        out = {"accepted": accepted, "rejected": rejected}
        wait_s = doc.get("wait_s")
        if wait_s is not None:
            completed, warm_bytes, timed_out = 0, 0, False
            deadline = time.monotonic() + max(0.0, float(wait_s))
            for t in tickets:
                try:
                    _, data = self.service.result(
                        t, timeout=max(0.0, deadline - time.monotonic()))
                    completed += 1
                    warm_bytes += int(getattr(data, "nbytes", 0) or 0)
                except TimeoutError:
                    timed_out = True  # budget burned; rest stay queued
                    break
                except Exception:  # noqa: BLE001 — a failed warm is cold
                    pass
            out.update(completed=completed, bytes=warm_bytes,
                       timed_out=timed_out)
        # /warm negotiates like /product (ISSUE 16) — its 202 body is
        # JSON either way (recipes in, counts out: nothing to frame),
        # so the header honestly answers "json" even to binary askers.
        return _json_resp(202, out, {WIRE_HEADER: "json"})

    # -- surfaces ----------------------------------------------------------
    def health(self) -> Dict:
        """The peer's ``/healthz`` body: the monitor plane's honest
        document, degraded further while this peer drains."""
        doc = self._pub.health()
        if self.service.draining():
            doc["reasons"] = list(doc.get("reasons") or []) + ["draining"]
            doc["ok"] = False
            doc["status"] = "degraded"
        doc["name"] = self.name
        return doc

    def stats(self) -> Dict:
        s = self.service.stats()
        s["name"] = self.name
        s["hot"] = self.service.cache.hot(8)
        with self._counts_lock:
            s["http"] = dict(self.counts)
        return s

    def snapshot(self) -> Dict:
        """This peer's ``/snapshot`` body (:func:`snapshot_with`)."""
        return snapshot_with(self.service.timeline, self.name)

    # -- lifecycle ---------------------------------------------------------
    def _beat_loop(self) -> None:
        while not self._beat_stop.wait(self._beat_interval_s):
            try:
                self._lease.beat()
            except OSError:
                log.warning("peer lease beat failed", exc_info=True)

    def start(self) -> "PeerServer":
        if self._server_thread is None:
            self._server_thread = threading.Thread(
                target=self._server.serve_forever,
                name=f"blit-peer-{self.name}", daemon=True)
            self._server_thread.start()
        if self._lease is not None and self._beat_thread is None:
            self._lease.beat()  # bring-up beat: alive before first tick
            self._beat_thread = threading.Thread(
                target=self._beat_loop, name=f"blit-peer-{self.name}-beat",
                daemon=True)
            self._beat_thread.start()
        return self

    def drain(self, timeout: Optional[float] = 30.0) -> Dict[str, int]:
        """Graceful drain: the service refuses new work and finishes
        in-flight (releasing live-session holds); the lease KEEPS
        beating and ``/healthz`` answers degraded-draining, so the
        front door routes around an announced shutdown instead of
        burning its lease TTL discovering it."""
        return self.service.drain(timeout)

    def close(self) -> None:
        self._beat_stop.set()
        if self._beat_thread is not None:
            self._beat_thread.join(timeout=2.0)
            self._beat_thread = None
        self._server.shutdown()
        self._server.server_close()
        self._server.close_all_connections()
        self._server_thread = None
        self._pub.close()
        if self.request_log is not None:
            self.request_log.close()

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.close()


# -- the front door as HTTP --------------------------------------------------


class FrontDoorServer:
    """The fleet front door (:class:`blit.serve.fleet.FleetFrontDoor`)
    served over HTTP: same ``/product`` wire as a peer (clients cannot
    tell one host from a fleet), aggregated ``/healthz``
    (:func:`blit.monitor.fold_health` — one probe answers "is the fleet
    serving"), ``/metrics`` with the routing counters, ``/stats``, and
    ``POST /drain``."""

    def __init__(self, door, *, port: int = 0, host: str = "127.0.0.1"):
        self.door = door
        self._server = _make_server(self._route, port, host)
        self.port = self._server.server_address[1]
        adv = "127.0.0.1" if host in ("127.0.0.1", "localhost") else host
        self.url = f"http://{adv}:{self.port}"
        self._server_thread: Optional[threading.Thread] = None

    def _route(self, method: str, path: str, doc: Optional[Dict],
               headers: Optional[Dict] = None) -> Tuple:
        if method == "GET" and path.startswith("/healthz"):
            return _json_resp(200, self.door.health())
        if method == "GET" and path.startswith("/metrics"):
            from blit.observability import (
                OPENMETRICS_CTYPE,
                PROM_CTYPE,
                wants_openmetrics,
            )

            om = wants_openmetrics((headers or {}).get("accept"))
            return (200, self.door.metrics_prometheus(openmetrics=om),
                    OPENMETRICS_CTYPE if om else PROM_CTYPE, {})
        if method == "GET" and path.startswith("/stats"):
            return _json_resp(200, self.door.stats())
        if method == "GET" and path.startswith("/snapshot"):
            return _json_resp(200, snapshot_with(self.door.timeline,
                                                 "door"))
        if method == "GET" and path.startswith("/history"):
            # Fleet-wide history: fan the range query out to every
            # live peer and fold the answers (ISSUE 20) — one query
            # surface for "what did the FLEET look like last Tuesday".
            since, until, tier = history_query(path)
            return _json_resp(200, self.door.history(since, until,
                                                     tier=tier))
        if method == "POST" and path.startswith("/product"):
            # An external client's trace continues through the door
            # (ISSUE 15): activate its context so the door's
            # fleet.request span — and everything downstream — parents
            # onto it.
            tr = observability.tracer()
            binary = wants_binary_product((headers or {}).get("accept"))
            try:
                with tr.activate(trace_context_from(headers)):
                    req, priority, client, deadline_s = request_from_wire(
                        doc or {})
                    header, data = self.door.get(
                        req, priority=priority, client=client,
                        deadline_s=deadline_s)
            except BaseException as e:  # noqa: BLE001 — mapped
                return _error_resp(e)
            if binary:
                # Zero-copy to the client: prefix + payload buffer
                # written straight through (_make_server), no joined
                # body copy of the product bytes.
                return (200, encode_product_parts(header, data),
                        WIRE_CTYPE, {WIRE_HEADER: "binary"})
            return _json_resp(200, encode_product(header, data),
                              {WIRE_HEADER: "json"})
        if method == "POST" and path.startswith("/drain"):
            threading.Thread(target=self.door.drain,
                             name="blit-door-drain", daemon=True).start()
            return _json_resp(200, {"draining": True})
        return _json_resp(404, {"error": f"no route {method} {path}"})

    def start(self) -> "FrontDoorServer":
        if self._server_thread is None:
            self._server_thread = threading.Thread(
                target=self._server.serve_forever, name="blit-front-door",
                daemon=True)
            self._server_thread.start()
        return self

    def close(self) -> None:
        self._server.shutdown()
        self._server.server_close()
        self._server.close_all_connections()
        self._server_thread = None

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.close()


# -- signal wiring -----------------------------------------------------------


def install_drain_handler(drain_fn: Callable[[], object], *,
                          exit_after: bool = True,
                          signals: Optional[Tuple] = None):
    """Wire SIGTERM/SIGINT to a graceful drain (ISSUE 14 satellite):
    the FIRST signal runs ``drain_fn`` (refuse new, finish in-flight,
    release ``kind="stream"`` holds) and then — with ``exit_after`` —
    raises ``SystemExit(128+signum)``; a SECOND signal while draining
    exits immediately (the operator's escalation path).  Returns an
    uninstall callable restoring the previous handlers.  No-ops (and
    returns a no-op) off the main thread, where CPython forbids signal
    installation."""
    import signal as _signal

    if signals is None:
        signals = (_signal.SIGTERM, _signal.SIGINT)
    prev = {}
    state = {"fired": False}

    def _handler(signum, frame):
        if state["fired"]:
            raise SystemExit(128 + signum)
        state["fired"] = True
        log.warning("signal %s: draining (second signal exits now)",
                    signum)
        try:
            drain_fn()
        finally:
            if exit_after:
                raise SystemExit(128 + signum)

    for s in signals:
        try:
            prev[s] = _signal.signal(s, _handler)
        except (ValueError, OSError):  # not the main thread
            pass

    def uninstall():
        for s, h in prev.items():
            try:
                _signal.signal(s, h)
            except (ValueError, OSError):
                pass

    return uninstall


# -- wait helpers (bench/chaos bring-up) -------------------------------------


def wait_http_ready(url: str, path: str = "/healthz",
                    timeout_s: float = 30.0,
                    poll_s: float = 0.05) -> Dict:
    """Poll ``url+path`` until it answers 200 (→ the parsed body) or
    the budget burns (``TimeoutError``) — the bench/chaos bring-up
    barrier for peer subprocesses."""
    deadline = time.monotonic() + timeout_s
    last: Optional[str] = None
    while time.monotonic() < deadline:
        try:
            status, _, body = http_json("GET", url, path, timeout=2.0)
            if status == 200:
                return body if isinstance(body, dict) else {}
            last = f"HTTP {status}"
        except OSError as e:
            last = str(e)
        time.sleep(poll_s)
    raise TimeoutError(f"{url}{path} not ready in {timeout_s}s ({last})")


def retry_after_from(headers: Dict[str, str], body: object) -> float:
    """The jittered back-off a 503 told us to honor: the JSON body's
    exact float when present, else the ``Retry-After`` header."""
    if isinstance(body, dict) and "retry_after_s" in body:
        return float(body["retry_after_s"])
    try:
        return float(headers.get("retry-after", 1.0))
    except ValueError:
        return 1.0


__all__ = [
    "ConnectionPool",
    "FrontDoorServer",
    "HEDGE_HEADER",
    "PeerServer",
    "REQUEST_ID_HEADER",
    "SPAN_HEADER",
    "TIER_HEADER",
    "TRACE_HEADER",
    "WIRE_CTYPE",
    "WIRE_HEADER",
    "WireError",
    "decode_product",
    "decode_product_wire",
    "encode_product",
    "encode_product_parts",
    "encode_product_wire",
    "http_json",
    "http_request",
    "install_drain_handler",
    "request_from_wire",
    "retry_after_from",
    "trace_context_from",
    "trace_headers",
    "wait_http_ready",
    "wants_binary_product",
    "wire_request",
]
