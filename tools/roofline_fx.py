"""Per-stage roofline of one FX-correlator `correlate` call on the real
chip (VERDICT r3 item 2: "the correlator leg is the one unjustified perf
number left — roofline it, then fuse or prove its ceiling").

Stages at the bench config (nant=8, nchan=64, nfft=512, ntap=4,
ntime=64*nfft, npol=2; blit/parallel/correlator.py):

  pfb x2        FIR frontend on the re and im planes
  dft           planar matmul DFT over the frame axis (fft_planar)
  xengine       baseline cross-products + frame sum (4 einsums)
  whole         jitted correlate() (XLA fuses across stage seams)

Byte accounting: the "min" column is the analytic minimum (read inputs
once, write outputs once, f32); achieved GB/s divides the sink-inclusive
bytes (`scalarized_bytes`: timed()'s on-device scalar sink re-reads each
stage's outputs once), the same convention as tools/roofline.py.

Run on the TPU rig:  python tools/roofline_fx.py [nant nchan nfft nblk reps]
"""

from __future__ import annotations

import os
import sys

import numpy as np

import jax
import jax.numpy as jnp

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from tools.roofline import (  # noqa: E402
    HBM_PEAK_GBPS,
    scalarized_bytes,
    time_whole,
    timed,
)


def main() -> None:
    nant = int(sys.argv[1]) if len(sys.argv) > 1 else 8
    nchan = int(sys.argv[2]) if len(sys.argv) > 2 else 64
    nfft = int(sys.argv[3]) if len(sys.argv) > 3 else 512
    nblk = int(sys.argv[4]) if len(sys.argv) > 4 else 64
    # The tunnel charges ~100 ms to the ONE closing fetch; stages here run
    # ~3-25 ms, so the default 6 reps would bury them in amortized fetch
    # latency (the filterbank roofline's 36 ms stages tolerate it; these
    # do not).  High reps make the per-rep latency share negligible.
    reps = int(sys.argv[5]) if len(sys.argv) > 5 else 32
    ntap, npol = 4, 2
    ntime = nblk * nfft
    nframes = nblk - ntap + 1

    cache = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), ".jax_cache")
    jax.config.update("jax_compilation_cache_dir", cache)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)

    from blit.ops.channelize import fft_planar, pfb_coeffs, pfb_frontend
    from blit.parallel import correlator as C
    from blit.parallel import mesh as M

    rng = np.random.default_rng(0)
    shape = (nant, nchan, npol, ntime)  # pol before time, as correlate does
    vr = jnp.asarray(rng.standard_normal(shape).astype(np.float32))
    vi = jnp.asarray(rng.standard_normal(shape).astype(np.float32))
    coeffs = pfb_coeffs(ntap, nfft).astype(np.float32)
    sign = np.where(np.arange(nfft) % 2 == 0, 1.0, -1.0).astype(np.float32)
    hj = jnp.asarray(coeffs * sign[None, :])

    plane = nant * nchan * npol * ntime * 4          # one f32 input plane
    spec = nant * nchan * npol * nframes * nfft * 4  # one spectra plane
    vis = nant * nant * nchan * nfft * npol * npol * 4

    rows = []

    def report(name, seconds, rd, wr):
        # timed()'s on-device scalar sink re-reads the outputs once per
        # rep: achieved bandwidth divides the SINK-inclusive bytes
        # (scalarized_bytes = rd + 2*wr), the shared roofline convention.
        moved = scalarized_bytes(rd, wr)
        rows.append((name, seconds, moved / seconds / 1e9))
        print(f"{name:24s} {seconds * 1e3:8.2f} ms   min {(rd + wr) / 1e6:9.1f} MB"
              f"   (+sink {moved / 1e6:9.1f})"
              f"   {moved / seconds / 1e9:7.1f} GB/s of {HBM_PEAK_GBPS:.0f}",
              flush=True)

    # Stage 1: FIR on both planes.
    t, (fr, fi) = timed(
        lambda a, b: (pfb_frontend(a, hj), pfb_frontend(b, hj)), vr, vi,
        reps=reps,
    )
    report("pfb x2 (fir)", t, 2 * plane, 2 * spec)

    # Stage 2: planar matmul DFT on the framed planes.
    t, (sr, si) = timed(lambda a, b: fft_planar(a, b), fr, fi,
                        reps=reps)
    report("dft (planar matmul)", t, 2 * spec, 2 * spec)

    # Stage 3: X-engine cross products.
    t, _ = timed(lambda a, b: C._xengine_planar(a, b), sr, si,
                 reps=reps)
    report("xengine (4 einsums)", t, 2 * spec, 2 * vis)
    del fr, fi, sr, si

    # Whole jitted correlate on a 1x1 mesh (the bench path).
    mesh = M.make_mesh(1, 1)
    vr4 = jnp.moveaxis(vr, 2, 3)  # (a, c, t, p): correlate's input layout
    vi4 = jnp.moveaxis(vi, 2, 3)
    vp = jax.device_put(
        (jax.block_until_ready(vr4), jax.block_until_ready(vi4)),
        C.correlator_sharding(mesh),
    )
    hplain = jnp.asarray(coeffs)

    def whole(pair):
        a, b = C.correlate(pair, hplain, mesh=mesh, nfft=nfft, ntap=ntap)
        return jnp.sum(a) + jnp.sum(b)

    sec, compile_s = time_whole(whole, vp, reps=reps)
    input_bytes = 2 * plane
    print(f"{'whole correlate':24s} {sec * 1e3:8.2f} ms   "
          f"input {input_bytes / 1e6:9.1f} MB   "
          f"{input_bytes / sec / 1e9:7.1f} GB/s input rate "
          f"(compile {compile_s:.1f}s)", flush=True)
    ssum = sum(r[1] for r in rows)
    print(f"{'sum of stages':24s} {ssum * 1e3:8.2f} ms")
    min_total = (2 * plane + 2 * spec) + (4 * spec) + (2 * spec + 2 * vis)
    print(f"analytic min traffic {min_total / 1e6:.1f} MB "
          f"→ bound {min_total / HBM_PEAK_GBPS / 1e9 * 1e3:.2f} ms/call; "
          f"whole-call implies {input_bytes / sec / 1e9:.2f} GB/s input")


if __name__ == "__main__":
    main()
