"""Pallas VMEM-resident X-engine prototype vs the production einsum
X-engine, interleaved on-chip at nant=64 (VERDICT r4 item 1: "build the
VMEM-resident X-engine if the measured shape justifies it, or record the
dead end at that shape").

The kernel consumes spectra pre-transposed (ONE XLA pass) to
``(nchan, nfft, nant*npol, nframes)`` and emits packed visibilities
``(nchan, nfft, ap, bq)``: per (chan, fine-tile) grid step it loads both
planes' (FT, 128, nframes) blocks into VMEM and runs 4 batched
dot_generals — every spectra byte is read exactly once, every visibility
byte written once.  tools/ab_fx64.py already measured packed-layout
OUTPUT parity for the einsum path, so the packed emission is not the
variable under test; the single-pass VMEM residency is.

Run on the TPU rig:  python tools/ab_fx64_pallas.py [nant nchan nfft nblk rounds reps ft]
"""

from __future__ import annotations

import functools
import os
import sys
import time

import numpy as np

import jax
import jax.numpy as jnp

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> int:
    nant = int(sys.argv[1]) if len(sys.argv) > 1 else 64
    nchan = int(sys.argv[2]) if len(sys.argv) > 2 else 16
    nfft = int(sys.argv[3]) if len(sys.argv) > 3 else 512
    nblk = int(sys.argv[4]) if len(sys.argv) > 4 else 64
    rounds = int(sys.argv[5]) if len(sys.argv) > 5 else 3
    reps = int(sys.argv[6]) if len(sys.argv) > 6 else 24
    ft = int(sys.argv[7]) if len(sys.argv) > 7 else 8
    ntap, npol = 4, 2
    ntime = nblk * nfft

    cache = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), ".jax_cache")
    jax.config.update("jax_compilation_cache_dir", cache)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)

    from blit.ops.channelize import pfb_coeffs
    # The SHIPPED kernel, not a prototype copy: re-running this tool keeps
    # measuring the code path correlate(vis_layout="packed") dispatches.
    from blit.ops.pallas_xengine import xengine_packed
    from blit.parallel.correlator import _xengine_planar, f_engine_planar

    rng = np.random.default_rng(0)
    shape = (nant, nchan, npol, ntime)
    vr = jnp.asarray(rng.standard_normal(shape).astype(np.float32))
    vi = jnp.asarray(rng.standard_normal(shape).astype(np.float32))
    hj = jnp.asarray(pfb_coeffs(ntap, nfft).astype(np.float32))
    nbytes = vr.nbytes + vi.nbytes

    xe_pl = functools.partial(xengine_packed, ft=ft)

    def make(xe):
        @jax.jit
        def f(a, b):
            sr, si = f_engine_planar(a, b, hj)
            visr, visi = xe(sr, si)
            return jnp.sum(visr) + jnp.sum(visi)

        return f

    fa = make(_xengine_planar)
    fb = make(xe_pl)
    t0 = time.time()
    ca, cb = float(fa(vr, vi)), float(fb(vr, vi))
    rel = abs(cb - ca) / max(abs(ca), 1e-9)
    print(f"warmup (incl. compile) {time.time() - t0:.1f}s "
          f"checksum delta {rel:.2e}", flush=True)
    # Both paths multiply at the TPU's default (bf16) matmul precision but
    # reduce in different orders; interpret-mode element-wise equality is
    # pinned separately, the chip checksum only guards gross breakage.
    assert rel < 1e-3, "pallas X-engine disagrees with the einsum path"

    def block(f):
        t0 = time.time()
        out = None
        for _ in range(reps):
            out = f(vr, vi)
        float(out)
        return reps * nbytes / (time.time() - t0) / 1e9

    ga, gb = [], []
    for r in range(rounds):
        ga.append(block(fa))
        gb.append(block(fb))
        print(f"round {r}: A {ga[-1]:.2f}  B(pallas ft={ft}) {gb[-1]:.2f} "
              "GB/s", flush=True)
    print(f"A einsum:  {min(ga):.2f}-{max(ga):.2f} GB/s "
          f"(median {np.median(ga):.2f})")
    print(f"B pallas:  {min(gb):.2f}-{max(gb):.2f} GB/s "
          f"(median {np.median(gb):.2f})")
    print(f"median ratio B/A: {np.median(gb) / np.median(ga):.3f}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
