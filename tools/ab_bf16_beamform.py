"""Interleaved on-chip A/B: beamforming from bf16-RESIDENT voltage planes
vs the production f32 planes (VERDICT r4 item 6: "bf16 collectives:
measure or bury").

Why residency is the variable: the TPU's default matmul precision already
multiplies f32 einsum operands at bf16 (measured — a plain f32
dot_general shows bf16-scale error vs NumPy), so casting inside the jit
changes nothing (tools/ab_fx64.py variant C: parity).  The lever is
HBM-resident bf16 operands — half the voltage read traffic and half the
ICI psum bytes.  Antenna voltages come from 8-bit RAW samples, whose
integer values bf16's 8-bit mantissa represents EXACTLY, so bf16
residency of the data plane is lossless for this workload; only the
weight phasors round.

  A  f32 planes + production beamform
  B  bf16 planes + bf16 step (psum in bf16, detection in f32)

Reports time/call and f32-equivalent input GB/s (same voltage content on
both sides), plus max relative error of the detected power.

Run on the TPU rig:  python tools/ab_bf16_beamform.py [nant nbeam nchan ntime nint rounds reps]
"""

from __future__ import annotations

import os
import sys
import time

import numpy as np

import jax

from blit.compat import shard_map
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> int:
    nant = int(sys.argv[1]) if len(sys.argv) > 1 else 64
    nbeam = int(sys.argv[2]) if len(sys.argv) > 2 else 64
    nchan = int(sys.argv[3]) if len(sys.argv) > 3 else 64
    ntime = int(sys.argv[4]) if len(sys.argv) > 4 else 8192
    nint = int(sys.argv[5]) if len(sys.argv) > 5 else 8
    rounds = int(sys.argv[6]) if len(sys.argv) > 6 else 3
    reps = int(sys.argv[7]) if len(sys.argv) > 7 else 48
    npol = 2

    cache = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), ".jax_cache")
    jax.config.update("jax_compilation_cache_dir", cache)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)

    from blit.ops.channelize import integrate
    from blit.parallel import beamform as B
    from blit.parallel import mesh as M

    mesh = M.make_mesh(1, 1)
    rng = np.random.default_rng(0)
    # 8-bit-quantized voltages, as RAW recordings deliver them: the int
    # values are exactly representable in bf16 (8 mantissa bits).
    v8 = rng.integers(-127, 128, (2, nant, nchan, ntime, npol)).astype(
        np.float32
    )
    wr, wi = B.delay_weights_planar(
        jnp.asarray(rng.uniform(0, 1e-9, (nbeam, nant))),
        jnp.asarray(np.linspace(1e9, 1.1e9, nchan)),
    )
    f32eq_bytes = 2 * v8[0].nbytes  # same content both sides

    vp32 = jax.device_put(
        (v8[0], v8[1]), B.antenna_sharding(mesh)
    )
    vp16 = jax.device_put(
        (v8[0].astype(jnp.bfloat16), v8[1].astype(jnp.bfloat16)),
        B.antenna_sharding(mesh),
    )
    wp32 = jax.device_put((np.asarray(wr), np.asarray(wi)),
                          B.weight_sharding(mesh))
    wp16 = jax.device_put(
        (np.asarray(wr).astype(jnp.bfloat16),
         np.asarray(wi).astype(jnp.bfloat16)),
        B.weight_sharding(mesh),
    )
    jax.block_until_ready((vp32, vp16, wp32, wp16))

    def fa(vp, wp):
        return B.beamform(vp, wp, mesh=mesh, nint=nint)

    @jax.jit
    def fb(vp, wp):
        vr, vi = vp
        wr, wi = wp

        def step(vr, vi, wr, wi):
            rr = jnp.einsum("bac,actp->bctp", wr, vr)
            ii = jnp.einsum("bac,actp->bctp", wi, vi)
            ri = jnp.einsum("bac,actp->bctp", wr, vi)
            ir = jnp.einsum("bac,actp->bctp", wi, vr)
            br, bi = rr - ii, ri + ir  # bf16 partial beams
            br, bi = jax.lax.psum((br, bi), "bank")  # bf16 on the wire
            br = br.astype(jnp.float32)
            bi = bi.astype(jnp.float32)
            return integrate(br**2 + bi**2, nint)

        return shard_map(
            step, mesh=mesh,
            in_specs=(P("bank"), P("bank"), P(None, "bank"),
                      P(None, "bank")),
            out_specs=P(), check_vma=False,
        )(vr, vi, wr, wi)

    t0 = time.time()
    pa = np.asarray(fa(vp32, wp32))
    pb = np.asarray(fb(vp16, wp16))
    err = np.abs(pb - pa) / np.maximum(np.abs(pa), 1e-6)
    print(f"warmup (incl. compile) {time.time() - t0:.1f}s  "
          f"detected-power max rel err {err.max():.2e} "
          f"mean {err.mean():.2e}", flush=True)

    def block(f, vp, wp):
        t0 = time.time()
        out = None
        for _ in range(reps):
            out = jnp.sum(f(vp, wp))
        float(out)
        return reps * f32eq_bytes / (time.time() - t0) / 1e9

    ga, gb = [], []
    for r in range(rounds):
        ga.append(block(fa, vp32, wp32))
        gb.append(block(fb, vp16, wp16))
        print(f"round {r}: A(f32) {ga[-1]:.2f}  B(bf16) {gb[-1]:.2f} "
              "GB/s(f32-eq)", flush=True)
    print(f"A f32 : {min(ga):.2f}-{max(ga):.2f} (median {np.median(ga):.2f})")
    print(f"B bf16: {min(gb):.2f}-{max(gb):.2f} (median {np.median(gb):.2f})")
    print(f"median ratio B/A: {np.median(gb) / np.median(ga):.3f}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
