"""Interleaved on-chip A/B of FX-correlator variants AT ARRAY SCALE
(nant=64 — VERDICT r4 item 1: the X-engine decision was made on nant=8
evidence; at 64 antennas the per-(chan, fine) matmul is 128², exactly
MXU-sized, and must be re-measured).

Same interleaving + single-fetch methodology as tools/ab_fx.py
(rig variance ±25%: never compare across processes; DESIGN.md §9).

Variants (whole jitted F+X call, input GB/s; sum() sink is
layout-invariant so checksums cross-check the math):

  A  split4/standard   production: 4 einsums -> (a,b,c,f,p,q)
  B  split4/packed     4 einsums  -> (c,f,a,p,b,q) — skips the
                       visibility post-transpose XLA performs for the
                       standard layout (the roofline's 5x gap to the
                       4.47 ms analytic bound is layout traffic, not
                       MXU work)
  C  packed + bf16     B with spectra cast to bf16 before the X-engine
                       (MXU-native dots, f32 accumulation): halves the
                       X-engine's spectra read traffic

Run on the TPU rig:  python tools/ab_fx64.py [nant nchan nfft nblk rounds reps]
"""

from __future__ import annotations

import os
import sys
import time

import numpy as np

import jax
import jax.numpy as jnp

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> int:
    nant = int(sys.argv[1]) if len(sys.argv) > 1 else 64
    nchan = int(sys.argv[2]) if len(sys.argv) > 2 else 16
    nfft = int(sys.argv[3]) if len(sys.argv) > 3 else 512
    nblk = int(sys.argv[4]) if len(sys.argv) > 4 else 64
    rounds = int(sys.argv[5]) if len(sys.argv) > 5 else 3
    reps = int(sys.argv[6]) if len(sys.argv) > 6 else 24
    ntap, npol = 4, 2
    ntime = nblk * nfft

    cache = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), ".jax_cache")
    jax.config.update("jax_compilation_cache_dir", cache)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)

    from blit.ops.channelize import pfb_coeffs
    from blit.parallel.correlator import _xengine_planar, f_engine_planar

    rng = np.random.default_rng(0)
    shape = (nant, nchan, npol, ntime)
    vr = jnp.asarray(rng.standard_normal(shape).astype(np.float32))
    vi = jnp.asarray(rng.standard_normal(shape).astype(np.float32))
    hj = jnp.asarray(pfb_coeffs(ntap, nfft).astype(np.float32))
    nbytes = vr.nbytes + vi.nbytes

    def xengine_packed(sr, si):
        rr = jnp.einsum("acptf,bcqtf->cfapbq", sr, sr)
        ii = jnp.einsum("acptf,bcqtf->cfapbq", si, si)
        ir = jnp.einsum("acptf,bcqtf->cfapbq", si, sr)
        ri = jnp.einsum("acptf,bcqtf->cfapbq", sr, si)
        return rr + ii, ir - ri

    def xengine_packed_bf16(sr, si):
        sr = sr.astype(jnp.bfloat16)
        si = si.astype(jnp.bfloat16)
        kw = dict(preferred_element_type=jnp.float32)
        rr = jnp.einsum("acptf,bcqtf->cfapbq", sr, sr, **kw)
        ii = jnp.einsum("acptf,bcqtf->cfapbq", si, si, **kw)
        ir = jnp.einsum("acptf,bcqtf->cfapbq", si, sr, **kw)
        ri = jnp.einsum("acptf,bcqtf->cfapbq", sr, si, **kw)
        return rr + ii, ir - ri

    def make(xe):
        @jax.jit
        def f(a, b):
            sr, si = f_engine_planar(a, b, hj)
            visr, visi = xe(sr, si)
            return jnp.sum(visr) + jnp.sum(visi)

        return f

    fa = make(_xengine_planar)  # production
    fb = make(xengine_packed)
    fc = make(xengine_packed_bf16)
    t0 = time.time()
    ca, cb, cc = float(fa(vr, vi)), float(fb(vr, vi)), float(fc(vr, vi))
    print(f"warmup (incl. compile) {time.time() - t0:.1f}s", flush=True)
    print(f"checksum B/A delta {abs(cb - ca) / max(abs(ca), 1e-9):.2e}  "
          f"C/A delta {abs(cc - ca) / max(abs(ca), 1e-9):.2e}", flush=True)

    def block(f):
        t0 = time.time()
        out = None
        for _ in range(reps):
            out = f(vr, vi)
        float(out)
        return reps * nbytes / (time.time() - t0) / 1e9

    gs = {"A": [], "B": [], "C": []}
    for r in range(rounds):
        gs["A"].append(block(fa))
        gs["B"].append(block(fb))
        gs["C"].append(block(fc))
        print(f"round {r}: A {gs['A'][-1]:.2f}  B {gs['B'][-1]:.2f}  "
              f"C {gs['C'][-1]:.2f} GB/s", flush=True)
    for k, label in (("A", "split4/standard"), ("B", "split4/packed"),
                     ("C", "packed+bf16")):
        print(f"{k} {label:18s} {min(gs[k]):.2f}-{max(gs[k]):.2f} GB/s "
              f"(median {np.median(gs[k]):.2f})")
    print(f"median ratio B/A: {np.median(gs['B']) / np.median(gs['A']):.3f}  "
          f"C/A: {np.median(gs['C']) / np.median(gs['A']):.3f}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
