"""Pallas fused beamform+detect prototype vs the production einsum path,
interleaved on-chip (round 5: the beamform leg runs ~84 GB/s f32-eq bf16
against a ~0.6 GB fully-fused minimum — the einsum path materializes the
(nbeam, nchan, ntime, npol) beam planes in HBM twice, then reads them
back for detection).

Kernel: grid (nchan, ntime tiles).  Per step it holds the chan's weights
(nbeam, nant) and one time tile of voltages (nant, npol, T) in VMEM,
forms the four real products as dot_generals, squares, and integrates by
``nint`` via a static 0/1 block-diagonal matmul on the MXU (reshaping the
lane axis is a mosaic no-go; a matmul against S (T, T/nint) is not).
Beam planes never exist in HBM — voltages are read once, the integrated
power written once.

Layouts: voltages (nchan, nant, npol, ntime) [pol before time, lane=T],
weights (nchan, nbeam, nant), output (nchan, nbeam, npol, ntime/nint) —
packed, chan-major; the public API's (nbeam, nchan, t, npol) is one
cheap transpose of the SMALL output if a consumer needs it.

Run on the TPU rig:
  python tools/ab_pallas_beamform.py [nant nbeam nchan ntime nint rounds reps tile dtype]
"""

from __future__ import annotations

import os
import sys
import time

import numpy as np

import jax
import jax.numpy as jnp

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def make_fused(nint, tile):
    """The SHIPPED kernel (blit/ops/pallas_beamform.py), not a prototype
    copy: re-running this tool keeps measuring what
    ``beamform(layout="chan")`` dispatches."""
    from blit.ops.pallas_beamform import fused_beamform_detect

    def fused(vr, vi, wr, wi):
        return fused_beamform_detect(vr, vi, wr, wi, nint=nint, tile=tile)

    return fused


def main() -> int:
    nant = int(sys.argv[1]) if len(sys.argv) > 1 else 64
    nbeam = int(sys.argv[2]) if len(sys.argv) > 2 else 64
    nchan = int(sys.argv[3]) if len(sys.argv) > 3 else 64
    ntime = int(sys.argv[4]) if len(sys.argv) > 4 else 8192
    nint = int(sys.argv[5]) if len(sys.argv) > 5 else 8
    rounds = int(sys.argv[6]) if len(sys.argv) > 6 else 3
    reps = int(sys.argv[7]) if len(sys.argv) > 7 else 48
    # Default follows the kernel's output-lane rule (tile = nint*128);
    # DESIGN.md's numbers were measured at nint=8 -> 1024.
    tile = int(sys.argv[8]) if len(sys.argv) > 8 else nint * 128
    dtype = sys.argv[9] if len(sys.argv) > 9 else "bfloat16"
    npol = 2

    cache = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), ".jax_cache")
    jax.config.update("jax_compilation_cache_dir", cache)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)

    from blit.parallel import beamform as B
    from blit.parallel import mesh as M

    mesh = M.make_mesh(1, 1)
    rng = np.random.default_rng(0)
    v8 = rng.integers(-127, 128, (2, nant, nchan, ntime, npol)).astype(
        np.float32
    )
    wr, wi = B.delay_weights_planar(
        jnp.asarray(rng.uniform(0, 1e-9, (nbeam, nant))),
        jnp.asarray(np.linspace(1e9, 1.1e9, nchan)),
    )
    f32eq_bytes = 2 * v8[0].nbytes

    # Production path operands (API layout).
    vp = jax.device_put(
        (v8[0].astype(dtype), v8[1].astype(dtype)), B.antenna_sharding(mesh)
    )
    wp = jax.device_put((np.asarray(wr), np.asarray(wi)),
                        B.weight_sharding(mesh))

    # Kernel operands: (c, a, p, t) voltages, (c, b, a) weights.
    def pack_v(x):
        # host-side transpose: the kernel operands are materialized in
        # their packed layout (np.ascontiguousarray), not a lazy view.
        return jnp.asarray(np.ascontiguousarray(
            np.transpose(x, (1, 0, 3, 2))).astype(dtype))

    kvr, kvi = pack_v(v8[0]), pack_v(v8[1])
    kwr = jnp.asarray(np.ascontiguousarray(
        np.transpose(np.asarray(wr), (2, 0, 1))).astype(dtype))
    kwi = jnp.asarray(np.ascontiguousarray(
        np.transpose(np.asarray(wi), (2, 0, 1))).astype(dtype))
    jax.block_until_ready((vp, wp, kvr, kvi, kwr, kwi))

    fused = make_fused(nint, tile)

    def fa():
        return jnp.sum(B.beamform(vp, wp, mesh=mesh, nint=nint))

    def fb():
        return jnp.sum(fused(kvr, kvi, kwr, kwi))

    t0 = time.time()
    pa = np.asarray(B.beamform(vp, wp, mesh=mesh, nint=nint))
    pb = np.asarray(fused(kvr, kvi, kwr, kwi))
    # fused output (c, b, p, t/nint) -> API (b, c, t/nint, p)
    pb_api = np.transpose(pb, (1, 0, 3, 2))
    err = np.abs(pb_api - pa).max() / max(np.abs(pa).max(), 1e-9)
    print(f"warmup (incl. compile) {time.time() - t0:.1f}s  "
          f"max rel err vs production {err:.2e}", flush=True)
    assert err < 3e-2, err

    def block(f):
        t0 = time.time()
        out = None
        for _ in range(reps):
            out = f()
        float(out)
        return reps * f32eq_bytes / (time.time() - t0) / 1e9

    ga, gb = [], []
    for r in range(rounds):
        ga.append(block(fa))
        gb.append(block(fb))
        print(f"round {r}: A(einsum {dtype}) {ga[-1]:.2f}  "
              f"B(pallas tile={tile}) {gb[-1]:.2f} GB/s(f32-eq)", flush=True)
    print(f"A einsum: {min(ga):.2f}-{max(ga):.2f} (median {np.median(ga):.2f})")
    print(f"B pallas: {min(gb):.2f}-{max(gb):.2f} (median {np.median(gb):.2f})")
    print(f"median ratio B/A: {np.median(gb) / np.median(ga):.3f}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
