"""Interleaved on-chip A/B of two `channelize` kwarg variants.

The rig's run-to-run variance is ±25% (DESIGN.md §9 item 6), so kernel
comparisons are honest only when the variants interleave in ONE process:
A-block, B-block, A-block, ... with each block timed by the §9
methodology — per-call device-side scalar sink, K calls enqueued
back-to-back, exactly one scalar fetch closing the window (the in-order
queue guarantees all enqueued calls executed; per-rep fetches would time
the tunnel's ~100 ms RPC latency instead of the chip).

Usage (note: "auto" resolves to the fused tail+detect whenever eligible,
so pin the baseline's kernels explicitly — e.g. the tail-only kernel is
detect_kernel="xla"):
    python tools/ab_channelize.py \
        '{"tail_kernel": "pallas", "detect_kernel": "xla"}' \
        '{"tail_kernel": "pallas", "detect_kernel": "pallas"}' \
        [nchan frames dtype rounds K]

A variant may also override the dispatch shape itself with the pseudo
kwargs "nchan"/"frames" (popped before the channelize call), e.g.
'{"nchan": 64}' A/Bs 64 coarse channels against the base shape at equal
net-bytes accounting.  Prints per-round GB/s and the pooled summary.
"""

from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

import jax
import jax.numpy as jnp

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main(argv) -> int:
    if len(argv) < 3:
        print(__doc__, file=sys.stderr)
        print("error: need two JSON kwarg variants", file=sys.stderr)
        return 2
    try:
        kw_a = json.loads(argv[1])
        kw_b = json.loads(argv[2])
    except json.JSONDecodeError as e:
        print(f"error: variant is not valid JSON: {e}", file=sys.stderr)
        return 2
    nchan = int(argv[3]) if len(argv) > 3 else 48
    frames = int(argv[4]) if len(argv) > 4 else 8
    dtype = argv[5] if len(argv) > 5 else "bfloat16"
    rounds = int(argv[6]) if len(argv) > 6 else 3
    reps = int(argv[7]) if len(argv) > 7 else 4

    cache = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), ".jax_cache")
    jax.config.update("jax_compilation_cache_dir", cache)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)

    from blit.ops.channelize import channelize, pfb_coeffs

    nfft, ntap = 1 << 20, 4
    coeffs = jnp.asarray(pfb_coeffs(ntap, nfft))
    base = dict(nfft=nfft, ntap=ntap, nint=1, stokes="I",
                fft_method="auto", dtype=dtype)

    inputs = {}  # (nchan, frames) -> shared device array: equal shapes
    # time the SAME tensor, and distinct shapes don't double input HBM.

    def make(kw):
        kw = dict(kw)
        nc = int(kw.pop("nchan", nchan))
        fr = int(kw.pop("frames", frames))
        if (nc, fr) not in inputs:
            ntime = (ntap - 1 + fr) * nfft
            inputs[(nc, fr)] = jnp.asarray(np.random.default_rng(0).integers(
                -40, 40, size=(nc, ntime, 2, 2), dtype=np.int8))
        merged = {**base, **kw}

        @jax.jit
        def f(x):
            return jnp.sum(channelize(x, coeffs, **merged))

        return f, inputs[(nc, fr)], fr * nfft * nc * 4  # int8 2pol×re/im

    fa, va, na = make(kw_a)
    fb, vb, nb = make(kw_b)
    # Warm both (compile + first-run allocs), then one fetch each.
    t0 = time.time()
    float(fa(va))
    float(fb(vb))
    print(f"warmup (incl. compile) {time.time() - t0:.1f}s", flush=True)

    def block(f, v, net_bytes):
        t0 = time.time()
        out = None
        for _ in range(reps):
            out = f(v)
        float(out)  # one fetch; in-order queue ⇒ all reps executed
        dt = time.time() - t0
        return reps * net_bytes / dt / 1e9

    ga, gb = [], []
    for r in range(rounds):
        ga.append(block(fa, va, na))
        gb.append(block(fb, vb, nb))
        print(f"round {r}: A {ga[-1]:.2f}  B {gb[-1]:.2f} GB/s", flush=True)
    print(f"A {kw_a}: {min(ga):.2f}-{max(ga):.2f} GB/s")
    print(f"B {kw_b}: {min(gb):.2f}-{max(gb):.2f} GB/s")
    print(f"median ratio B/A: {np.median(gb) / np.median(ga):.3f}")
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv))
