"""Per-stage roofline of one `channelize` call on the real chip.

Times each pipeline stage separately under jit at the bench shapes and
compares the achieved HBM bandwidth against the analytic minimum traffic
(read every input once + write every output once).  The table this prints
backs DESIGN.md §9 — the evidence for where the next optimization dollar
goes (VERDICT round-2 "write the roofline, then attack it").

Run on the TPU rig:  python tools/roofline.py [nchan frames [dtype]]

Stages (f32 planar, factors (128, 128, 64) for nfft=2^20):
  dequant+pfb   int8 → planar f32 frames (windowed sums)
  dft1          128-pt DFT matmul + twiddle  (per recursion level 0)
  dft2          128-pt DFT matmul + twiddle  (level 1)
  dft3          64-pt DFT matmul             (level 2, innermost)
  untwist2/1    swapaxes+reshape epilogues of levels 1 and 0
  detect+int    |X|²+|Y|² detect (+ time integration) + product transpose
"""

from __future__ import annotations

import os
import sys
import time

import numpy as np

import jax
import jax.numpy as jnp

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from blit.ops import dft as D
from blit.ops.channelize import dequantize, pfb_coeffs, pfb_frontend, detect_stokes_planar, integrate

HBM_PEAK_GBPS = 819.0  # v5e spec number; the "roof"


def timed(fn, *args, reps=6):
    """Mean per-call device time of ``fn``, measured the only way that is
    honest on this rig: the tunnel charges ~100 ms latency to EVERY synced
    call (block_until_ready does not actually block here), so per-rep syncs
    time the tunnel and a queue of GB-sized outputs OOMs HBM.  Instead each
    rep reduces the stage outputs to one scalar ON DEVICE (a full extra
    read pass of the outputs — accounted by the caller via ``sum_rd``), K
    reps enqueue back-to-back, and one fetch at the end amortizes the
    latency across all reps.

    Also returns the stage's real outputs from one extra (untimed) call so
    the caller can chain stages."""
    g = jax.jit(lambda *a: sum(jnp.sum(o.astype(jnp.float32)) for o in
                               jax.tree.leaves(fn(*a))))
    float(g(*args))  # compile + settle
    t0 = time.perf_counter()
    acc = [g(*args) for _ in range(reps)]
    # ONE fetch: the in-order queue means the last scalar materializing
    # implies every rep executed; per-scalar fetches would charge each rep
    # the ~100 ms tunnel round trip even for already-computed results.
    float(acc[-1])
    per = (time.perf_counter() - t0) / reps
    out = jax.jit(fn)(*args)
    return per, out


def scalarized_bytes(rd: int, wr: int) -> int:
    """Bytes actually moved when a stage is timed through :func:`timed`'s
    on-device scalar sink: the harness re-reads the outputs once (+wr).
    Both report modes must use this same accounting."""
    return rd + 2 * wr


def time_whole(fn, vj, reps: int = 4):
    """Warm (compile) then time ``reps`` enqueued calls of the whole
    channelize with one closing fetch (the same tunnel-amortized rule as
    :func:`timed`).  Returns (seconds_per_call, compile_seconds)."""
    g = jax.jit(fn)
    t0 = time.perf_counter()
    float(g(vj))
    compile_s = time.perf_counter() - t0
    t0 = time.perf_counter()
    acc = [g(vj) for _ in range(reps)]
    float(acc[-1])
    return (time.perf_counter() - t0) / reps, compile_s


def fused_main(nchan: int, frames: int, dtype: str) -> None:
    """Per-pass decomposition of the FUSED production pipeline (the
    DESIGN.md §9 post-fusion table): pfb_dft1 → tail2_detect (+ its XLA
    lane swap, also isolated on a synthetic array) → whole channelize.

    Run:  python tools/roofline.py --fused [nchan frames [dtype]]
    """
    from blit.ops.channelize import _MATMUL_ONLY_BACKENDS, channelize
    from blit.ops.pallas_detect import tail2_detect
    from blit.ops.pallas_pfb import pfb_dft1

    nfft, ntap, npol = 1 << 20, 4, 2
    ntime = (ntap - 1 + frames) * nfft
    esize = 2 if dtype == "bfloat16" else 4
    rng = np.random.default_rng(0)
    v = rng.integers(-40, 40, (nchan, ntime, npol, 2), np.int8)
    vj = jax.block_until_ready(jnp.asarray(v))
    interp = jax.default_backend() not in _MATMUL_ONLY_BACKENDS
    factors = D.default_factors(nfft)
    n1 = factors[0]
    sign = np.where(np.arange(nfft) % 2 == 0, 1.0, -1.0).astype(np.float32)
    shifted = jnp.asarray(pfb_coeffs(ntap, nfft) * sign)
    w1r, w1i = (jnp.asarray(a) for a in D.dft_matrices(n1, "float32"))
    t1r, t1i = (jnp.asarray(a) for a in D.twiddles(n1, nfft // n1, "float32"))

    E = nchan * npol * frames * nfft
    plane = E * esize           # one (re or im) stage-1 plane
    power = E // npol * 4       # the f32 Stokes-I product

    print(f"fused roofline @ nchan={nchan} frames={frames} dtype={dtype}")

    def report(name, seconds, rd, wr):
        bts = scalarized_bytes(rd, wr)
        print(f"  {name:<28}{seconds * 1e3:>8.1f} ms  "
              f"{(rd + wr) / 1e9:>6.2f} GB  {bts / seconds / 1e9:>6.0f} GB/s",
              flush=True)

    t, (ur, ui) = timed(
        lambda x: pfb_dft1(x, shifted, w1r, w1i, t1r, t1i, dtype=dtype,
                           interpret=interp), vj)
    report("pfb_dft1 (int8->stage-1)", t, v.nbytes, 2 * plane)

    t, td_out = timed(
        lambda a, b: tail2_detect(a, b, factors[1], factors[2],
                                  interpret=interp), ur, ui)
    report("tail2_detect (+lane swap)", t, 2 * plane, power)
    del td_out

    # The lane swap isolated — models the Stokes-I case: tail2_detect's raw
    # output carries a nif axis (frames, nif, nchan, f3, f1, f2) which is
    # size 1 for "I" and folds away here; multi-pol products (nif=4) move
    # proportionally more bytes than this probe measures (ADVICE r3).
    x = jnp.zeros((frames, nchan, factors[2], factors[0], factors[1]),
                  jnp.float32)
    t, sw_out = timed(lambda y: jnp.swapaxes(y, -1, -2).reshape(
        frames, nchan, nfft), x)
    report("lane swap alone (xla)", t, power, power)
    # Free every stage array before the whole-call rerun — pinned planes
    # at these shapes are exactly the OOM-sensitive HBM margin (§9).
    del ur, ui, x, sw_out

    def whole(y):
        return jnp.sum(channelize(
            y, jnp.asarray(pfb_coeffs(ntap, nfft)), nfft=nfft, ntap=ntap,
            nint=1, stokes="I", fft_method="auto",
            **({} if dtype == "float32" else {"dtype": dtype})))

    whole_t, _compile_s = time_whole(whole, vj)
    net = frames * nfft * nchan * npol * 2
    print(f"  whole channelize: {whole_t * 1e3:.1f} ms, net {net / 1e9:.2f} GB"
          f" -> {net / whole_t / 1e9:.2f} GB/s/chip")


def main() -> None:
    if len(sys.argv) > 1 and sys.argv[1] == "--fused":
        args = sys.argv[2:]
        fused_main(
            int(args[0]) if len(args) > 0 else 48,
            int(args[1]) if len(args) > 1 else 8,
            args[2] if len(args) > 2 else "bfloat16",
        )
        return
    nchan = int(sys.argv[1]) if len(sys.argv) > 1 else 32
    frames = int(sys.argv[2]) if len(sys.argv) > 2 else 5
    dtype = sys.argv[3] if len(sys.argv) > 3 else "float32"
    nfft, ntap, npol = 1 << 20, 4, 2
    ntime = (ntap - 1 + frames) * nfft
    esize = 2 if dtype == "bfloat16" else 4

    rng = np.random.default_rng(0)
    v = rng.integers(-40, 40, (nchan, ntime, npol, 2), np.int8)
    coeffs = jnp.asarray(pfb_coeffs(ntap, nfft))
    vj = jax.block_until_ready(jnp.asarray(v))


    # Planar complex element count of one full intermediate.
    E = nchan * npol * frames * nfft
    plane = E * esize  # bytes of ONE (re or im) plane
    f32_plane = E * 4

    rows = []

    def row(name, seconds, rd, wr):
        bts = scalarized_bytes(rd, wr)
        rows.append((name, seconds, rd, wr, bts / seconds / 1e9))
        print(f"  {name}: {seconds * 1e3:.1f} ms, {bts / seconds / 1e9:.0f} GB/s",
              flush=True)

    # -- dequant + PFB (mirrors channelize: bf16 mode runs the whole stage
    # half-width, from the dequant planes on) ------------------------------
    work_dtype = jnp.bfloat16 if dtype == "bfloat16" else jnp.float32
    wcoeffs = coeffs.astype(work_dtype)

    def s_pfb(x):
        re, im = dequantize(x, dtype=work_dtype)
        re = jnp.moveaxis(re, -1, 1)
        im = jnp.moveaxis(im, -1, 1)
        fr = pfb_frontend(re, wcoeffs)
        fi = pfb_frontend(im, wcoeffs)
        return fr, fi

    t, (fr, fi) = timed(s_pfb, vj)
    row("dequant+pfb (xla)", t, v.nbytes, 2 * plane)
    frames_shape = fr.shape

    # The fused pallas variant (production default on the chip, §4/§9).
    if npol == 2:
        from blit.ops.channelize import _MATMUL_ONLY_BACKENDS
        from blit.ops.pallas_pfb import pfb_dequant

        interp = jax.default_backend() not in _MATMUL_ONLY_BACKENDS
        t, _ = timed(
            lambda x: pfb_dequant(x, coeffs, dtype=dtype, interpret=interp),
            vj,
        )
        row("dequant+pfb (pallas)", t, v.nbytes, 2 * plane)

    # -- DFT stages, timed one recursion level at a time -------------------
    # Intermediates are del'd as soon as the next stage's inputs exist: the
    # whole-pipeline HBM budget fits because XLA frees each stage's inputs;
    # a tool that pins every stage's output OOMs at the very shapes it is
    # supposed to measure.
    factors = D.default_factors(nfft)
    xr = jnp.reshape(fr, frames_shape[:-1] + (factors[0], nfft // factors[0]))
    xi = jnp.reshape(fi, frames_shape[:-1] + (factors[0], nfft // factors[0]))
    del fr, fi

    def stage_fn(n1, n2):
        w1r, w1i = (jnp.asarray(a) for a in D.dft_matrices(n1, dtype))
        tr, ti = (jnp.asarray(a) for a in D.twiddles(n1, n2, dtype))

        def f(ar_, ai_):
            a = jnp.einsum("kj,...jm->...km", w1r, ar_)
            b = jnp.einsum("kj,...jm->...km", w1i, ar_)
            c = jnp.einsum("kj,...jm->...km", w1r, ai_)
            d = jnp.einsum("kj,...jm->...km", w1i, ai_)
            sr, si = a - d, b + c
            return sr * tr - si * ti, sr * ti + si * tr

        return f

    rest = nfft
    level = 0
    while len(D.default_factors(rest)) > 1:
        n1 = D.default_factors(rest)[0]
        n2 = rest // n1
        t, (xr2, xi2) = timed(stage_fn(n1, n2), xr, xi)
        row(f"dft{level + 1} (n1={n1})", t, 2 * plane, 2 * plane)
        del xr, xi
        # reshape for the next level: rows stay batch, last axis splits again
        nf = D.default_factors(n2)[0]
        if len(D.default_factors(n2)) > 1:
            xr = xr2.reshape(xr2.shape[:-1] + (nf, n2 // nf))
            xi = xi2.reshape(xi2.shape[:-1] + (nf, n2 // nf))
        else:
            xr, xi = xr2, xi2
        del xr2, xi2
        rest = n2
        level += 1

    wlast = rest

    def last_fn(n):
        wr, wi = (jnp.asarray(a) for a in D.dft_matrices(n, dtype))

        def f(ar_, ai_):
            a = jnp.matmul(ar_, wr)
            b = jnp.matmul(ar_, wi)
            c = jnp.matmul(ai_, wr)
            d = jnp.matmul(ai_, wi)
            return a - d, b + c

        return f

    t, (yr, yi) = timed(last_fn(wlast), xr, xi)
    row(f"dft{level + 1} (n={wlast})", t, 2 * plane, 2 * plane)
    del xr, xi

    # -- the untwist transposes (swapaxes + reshape per level) -------------
    def untwist(ar_, ai_):
        # reshape after swapaxes forces materialization in the new layout
        # (jit outputs are default-layout, so this is the real transpose
        # cost the pipeline pays).
        a = jnp.swapaxes(ar_, -1, -2)
        b = jnp.swapaxes(ai_, -1, -2)
        flat = ar_.shape[:-2] + (ar_.shape[-1] * ar_.shape[-2],)
        return a.reshape(flat), b.reshape(flat)

    t, _ = timed(untwist, yr, yi)
    row("untwist (x1 of 2)", t, 2 * plane, 2 * plane)

    # -- detect + integrate + product transpose -----------------------------
    sr = yr.reshape(frames_shape)
    si = yi.reshape(frames_shape)
    del yr, yi

    def s_detect(ar_, ai_):
        if ar_.dtype != jnp.float32:
            ar_, ai_ = ar_.astype(jnp.float32), ai_.astype(jnp.float32)
        p = detect_stokes_planar(ar_, ai_, "I")
        p = integrate(p, 1)
        out = jnp.transpose(p, (2, 1, 0, 3))
        return out.reshape(out.shape[0], out.shape[1], -1)

    t, _ = timed(s_detect, sr, si)
    row("detect+transpose", t, 2 * plane, f32_plane // npol)
    del sr, si  # free the pinned stage arrays before the whole-call rerun

    # -- whole fused call for comparison ------------------------------------
    from blit.ops.channelize import channelize

    def whole(x):
        return jnp.sum(channelize(x, coeffs, nfft=nfft, ntap=ntap, nint=1,
                                  stokes="I", fft_method="auto",
                                  **({} if dtype == "float32" else {"dtype": dtype})))

    whole_t, compile_s = time_whole(whole, vj)

    net = frames * nfft * nchan * npol * 2  # int8 bytes credited by bench.py

    print(f"\nroofline @ nchan={nchan} frames={frames} nfft=2^20 dtype={dtype}"
          f"  (plane={plane / 1e9:.2f} GB, HBM peak {HBM_PEAK_GBPS:.0f} GB/s)")
    print(f"{'stage':<22}{'ms':>9}{'rd GB':>8}{'wr GB':>8}{'GB/s':>9}{'%roof':>7}")
    tot_ms = tot_bytes = 0.0
    for name, s, rd, wr, gbps in rows:
        n_un = 2 if name.startswith("untwist") else 1
        if "(pallas)" not in name:  # alternative stage, not an addend
            tot_ms += s * 1e3 * n_un
            tot_bytes += (rd + wr) * n_un
        print(f"{name:<22}{s * 1e3:>9.1f}{rd / 1e9:>8.2f}{wr / 1e9:>8.2f}"
              f"{gbps:>9.0f}{100 * gbps / HBM_PEAK_GBPS:>6.0f}%")
    print(f"{'sum of stages':<22}{tot_ms:>9.1f}  (analytic min traffic "
          f"{tot_bytes / 1e9:.1f} GB → {tot_bytes / HBM_PEAK_GBPS / 1e6:.1f} ms at roof)")
    print(f"{'whole channelize':<22}{whole_t * 1e3:>9.1f}  net {net / 1e9:.3f} GB"
          f" → {net / whole_t / 1e9:.2f} GB/s/chip  (compile {compile_s:.0f}s)")


if __name__ == "__main__":
    main()
