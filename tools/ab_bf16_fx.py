"""Interleaved on-chip A/B: bf16 stages in the packed-pallas FX correlator
at nant=64 (VERDICT r4 item 6, correlator half).

tools/ab_fx64.py measured in-jit bf16 casts at parity for the EINSUM
X-engine (no materialization boundary, so a cast changes no traffic).
The pallas path is different: the pack transpose materializes the
spectra between cast and kernel, so bf16 spectra halve that write, the
kernel's read, and its VMEM blocks.

  A  f32 spectra  -> pack -> pallas kernel (shipped round-5 path)
  B  bf16 spectra -> pack -> pallas kernel (dots accumulate f32)
  C  B + bf16-resident input voltages and bf16 FIR (maximal bf16 staging,
     mirroring the primary pipeline's bf16 stages — DESIGN.md §3/§8;
     8-bit RAW voltages are exact in bf16)

Accuracy is reported as max/mean relative error of visibilities vs A.

Run on the TPU rig:  python tools/ab_bf16_fx.py [nant nchan nfft nblk rounds reps]
"""

from __future__ import annotations

import os
import sys
import time

import numpy as np

import jax
import jax.numpy as jnp

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> int:
    nant = int(sys.argv[1]) if len(sys.argv) > 1 else 64
    nchan = int(sys.argv[2]) if len(sys.argv) > 2 else 16
    nfft = int(sys.argv[3]) if len(sys.argv) > 3 else 512
    nblk = int(sys.argv[4]) if len(sys.argv) > 4 else 64
    rounds = int(sys.argv[5]) if len(sys.argv) > 5 else 3
    reps = int(sys.argv[6]) if len(sys.argv) > 6 else 24
    ntap, npol = 4, 2
    ntime = nblk * nfft

    cache = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), ".jax_cache")
    jax.config.update("jax_compilation_cache_dir", cache)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)

    from blit.ops.channelize import pfb_coeffs
    from blit.ops.pallas_xengine import xengine_packed
    from blit.parallel.correlator import f_engine_planar

    rng = np.random.default_rng(0)
    shape = (nant, nchan, npol, ntime)
    v8 = rng.integers(-40, 41, (2,) + shape).astype(np.float32)
    vr32 = jnp.asarray(v8[0])
    vi32 = jnp.asarray(v8[1])
    vr16 = jnp.asarray(v8[0].astype(jnp.bfloat16))
    vi16 = jnp.asarray(v8[1].astype(jnp.bfloat16))
    hj = jnp.asarray(pfb_coeffs(ntap, nfft).astype(np.float32))
    f32eq_bytes = 2 * vr32.nbytes

    @jax.jit
    def fa(a, b):
        sr, si = f_engine_planar(a, b, hj)
        return xengine_packed(sr, si)

    @jax.jit
    def fb(a, b):
        sr, si = f_engine_planar(a, b, hj)
        return xengine_packed(sr.astype(jnp.bfloat16),
                              si.astype(jnp.bfloat16))

    @jax.jit
    def fc(a, b):
        sr, si = f_engine_planar(a, b, hj.astype(jnp.bfloat16))
        return xengine_packed(sr.astype(jnp.bfloat16),
                              si.astype(jnp.bfloat16))

    t0 = time.time()
    va = [np.asarray(x) for x in fa(vr32, vi32)]
    vb = [np.asarray(x) for x in fb(vr32, vi32)]
    vc = [np.asarray(x) for x in fc(vr16, vi16)]
    scale = max(np.abs(va[0]).max(), np.abs(va[1]).max())

    def err(v):
        return max(np.abs(v[0] - va[0]).max(), np.abs(v[1] - va[1]).max()) / scale

    print(f"warmup (incl. compile) {time.time() - t0:.1f}s  "
          f"rel err B {err(vb):.2e}  C {err(vc):.2e}", flush=True)

    def block(f, a, b):
        t0 = time.time()
        out = None
        for _ in range(reps):
            vr, vi = f(a, b)
            out = jnp.sum(vr) + jnp.sum(vi)
        float(out)
        return reps * f32eq_bytes / (time.time() - t0) / 1e9

    gs = {"A": [], "B": [], "C": []}
    for r in range(rounds):
        gs["A"].append(block(fa, vr32, vi32))
        gs["B"].append(block(fb, vr32, vi32))
        gs["C"].append(block(fc, vr16, vi16))
        print(f"round {r}: A {gs['A'][-1]:.2f}  B {gs['B'][-1]:.2f}  "
              f"C {gs['C'][-1]:.2f} GB/s(f32-eq)", flush=True)
    for k, label in (("A", "f32 spectra"), ("B", "bf16 spectra"),
                     ("C", "bf16 input+FIR+spectra")):
        print(f"{k} {label:22s} {min(gs[k]):.2f}-{max(gs[k]):.2f} "
              f"(median {np.median(gs[k]):.2f})")
    print(f"median ratio B/A: {np.median(gs['B']) / np.median(gs['A']):.3f}  "
          f"C/A: {np.median(gs['C']) / np.median(gs['A']):.3f}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
