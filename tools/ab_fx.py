"""Interleaved on-chip A/B of FX-correlator X-engine variants.

Same interleaving + single-fetch methodology as tools/ab_channelize.py
(the rig's ±25% run-to-run variance makes cross-process comparisons
meaningless; DESIGN.md §9 item 6).  Compares the whole jitted correlate
call — input GB/s — with the X-engine computed as:

  A  split4   four (nant·npol)² einsums over (re, im) pairs
  B  stacked  one (2·nant·npol)² einsum over the re/im-stacked operand

Run on the TPU rig:  python tools/ab_fx.py [nant nchan nfft nblk rounds reps]
"""

from __future__ import annotations

import os
import sys
import time

import numpy as np

import jax
import jax.numpy as jnp

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> int:
    nant = int(sys.argv[1]) if len(sys.argv) > 1 else 8
    nchan = int(sys.argv[2]) if len(sys.argv) > 2 else 64
    nfft = int(sys.argv[3]) if len(sys.argv) > 3 else 512
    nblk = int(sys.argv[4]) if len(sys.argv) > 4 else 64
    rounds = int(sys.argv[5]) if len(sys.argv) > 5 else 3
    reps = int(sys.argv[6]) if len(sys.argv) > 6 else 48
    ntap, npol = 4, 2
    ntime = nblk * nfft

    cache = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), ".jax_cache")
    jax.config.update("jax_compilation_cache_dir", cache)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)

    from blit.ops.channelize import pfb_coeffs
    from blit.parallel.correlator import _xengine_planar, f_engine_planar

    rng = np.random.default_rng(0)
    shape = (nant, nchan, npol, ntime)
    vr = jnp.asarray(rng.standard_normal(shape).astype(np.float32))
    vi = jnp.asarray(rng.standard_normal(shape).astype(np.float32))
    hj = jnp.asarray(pfb_coeffs(ntap, nfft).astype(np.float32))
    nbytes = vr.nbytes + vi.nbytes

    # Variant A IS the production kernel — imported, not copied, so this
    # A/B keeps describing what ships.
    xengine_split4 = _xengine_planar

    def xengine_stacked(sr, si):
        s2 = jnp.concatenate([sr, si], axis=2)
        big = jnp.einsum("acptf,bcqtf->abcfpq", s2, s2)
        rr = big[..., :npol, :npol]
        ii = big[..., npol:, npol:]
        ri = big[..., :npol, npol:]
        ir = big[..., npol:, :npol]
        return rr + ii, ir - ri

    def make(xe):
        @jax.jit
        def f(a, b):
            sr, si = f_engine_planar(a, b, hj)
            visr, visi = xe(sr, si)
            return jnp.sum(visr) + jnp.sum(visi)

        return f

    fa, fb = make(xengine_split4), make(xengine_stacked)
    t0 = time.time()
    ca, cb = float(fa(vr, vi)), float(fb(vr, vi))
    print(f"warmup (incl. compile) {time.time() - t0:.1f}s "
          f"checksum delta {abs(ca - cb) / max(abs(ca), 1e-9):.2e}",
          flush=True)

    def block(f):
        t0 = time.time()
        out = None
        for _ in range(reps):
            out = f(vr, vi)
        float(out)
        return reps * nbytes / (time.time() - t0) / 1e9

    ga, gb = [], []
    for r in range(rounds):
        ga.append(block(fa))
        gb.append(block(fb))
        print(f"round {r}: A {ga[-1]:.2f}  B {gb[-1]:.2f} GB/s", flush=True)
    print(f"A split4:  {min(ga):.2f}-{max(ga):.2f} GB/s")
    print(f"B stacked: {min(gb):.2f}-{max(gb):.2f} GB/s")
    print(f"median ratio B/A: {np.median(gb) / np.median(ga):.3f}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
